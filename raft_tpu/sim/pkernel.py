"""Pallas fused-chunk tick: many ticks per kernel launch, VMEM-resident.

DESIGN.md §7 measured the XLA tick HBM-bound: ~13 GFLOP but ~18 GB of
HBM traffic per tick at 100K groups, because every pass over the
[G, K, L] / [G, K, K] state re-materializes intermediates in HBM. Raft
groups never talk to each other (sim/state.py), so a *block* of groups
can run an arbitrary number of ticks entirely out of VMEM: this module
loads a block's full state once, runs a `lax.fori_loop` of whole ticks
over values held in vector registers/VMEM, and writes the block back
once. HBM traffic drops from O(ticks) full-state passes to one read +
one write per chunk, turning the simulation compute-bound.

Semantics are the SAME tick as `sim/step.py` — each helper here is a
line-for-line port of its namesake, with every feature statically
gated exactly as step.py gates it: crash / partition / drop faults,
the scheduled-read (ReadIndex) pipeline, single-server membership
change (derived config, voters-aware quorums, removed-leader
demotion), PreVote, and leadership transfer. The kernel is
feature-complete with the batched path INCLUDING metrics: the
election-latency histogram is tracked in-kernel as per-group
[H, 8, 128] accumulators (one-hot row add per tick, no scatter) and
reduced over groups at `kfinish`, so fault benches can ride the kernel
and report p50/p99 bit-identical to the XLA path (sim.run), which
remains the reference engine.
`tests/test_pkernel.py` holds the two paths bit-identical on full State
pytrees and metrics — histogram included — across fault mixes.

Telemetry is folded IN-KERNEL, not scraped host-side (DESIGN.md §8):
the per-tick safety bit (`_safety_tick`, the k-state port of
`check.tick_safety`) ANDs into a per-group KMetrics lane every tick for
a few vreg compares, and the optional flight-recorder ring
(raft_tpu/obs/recorder.py) overwrites one row of six per-group
[RING, 8, 128] accumulators per tick using the same one-hot-row pattern
the histogram landed — a host readback of either would dominate the
tick. Both are reduced/sliced host-side at kfinish/kflight and must be
bit-identical to the XLA fold (run.metrics_update /
obs.recorder.flight_update).

`_on_ae_req` is the fused form of step.py's handler (DESIGN.md §7b):
the four per-sender log-matching read passes (2E own-ring reads + 2E
sender-ring pulls per message) collapse into ONE packed elementwise
compare of the receiver's ring against the sender's, exploiting the
slot identity — an absolute index occupies ring slot (i-1) % L on
EVERY node, so the sender-side read slot and the receiver-side write
slot of an entry are the same slot, the write values are just the
sender's ring rows, and per-entry equality is a bit-select from the
packed compare. The own-ring `_abs_index` pass and its live-window
mask hoist to once per tick (snap_index cannot change before the
InstallSnapshot handler, which runs after all AE handlers). What does
NOT hoist across senders: reads of the receiver's log CONTENT — a
second same-tick AppendEntries (two leaders in adjacent terms) must
observe the first one's writes, exactly like step.py's and the CPU
oracle's sequential delivery, so the packed compare is per-sender
against the CURRENT ring.

Layout ("k-state"): the group axis folds into full vector registers: G
groups become a trailing [GS, 128] (sublane x lane) pair with
GS = G/128, so a per-node "scalar" is an [8, 128] tile inside the
kernel and every VPU op runs at full vreg utilization. (A first version
kept scalars as [1, G_blk] rows; it compiled and matched bit-exactly
but idled 7/8 sublanes and LOST to the XLA path — 50 vs 79 ticks/s at
100K.) Wire format per State leaf, the grid cutting 8-wide slices of
the GS axis:

  per-node scalar [G, K]    -> [K, GS, 128]      (per-node [8, 128])
  peer vector     [G, K, K] -> [K, K, GS, 128]   (per-node [K, 8, 128])
  log ring        [G, K, L] -> [K, L, GS, 128]   (per-node [L, 8, 128])
  mailbox         [G, d, s] -> [d, s, GS, 128]   (per-node [K_src, 8, 128])

Inside the kernel the per-node step is `jax.vmap`-ped over the node
axis, exactly like step.py's inner vmap; reductions step.py takes over
a trailing L/K axis happen over axis 0 here. Dynamic indexing stays
one-hot compare+select (Mosaic has no scatter lowering, and the XLA
path measured the same choice fastest).

Mosaic/LLO lowering rules learned the hard way (each cost a compile
failure; tests/test_pkernel.py guards them):
- no select against a scalar bool constant (i8->i1 trunci): bool
  updates use and/or masking (`_put`, freeze);
- no vector i1 CONSTANTS anywhere — including DEAD ones (a traced-but-
  unused jnp.zeros(bool) still lowers) and always-false iota compares
  (constant-folded back into i1 splats): all-false masks derive from
  runtime data (`g < 0`);
- no i1 loop carries (scf.for fails to legalize): bools widen to i32
  across the fori_loop boundary;
- no i1 transposes (mask relayout materializes constants LLO cannot
  build): the per-node outbox widens to i32 BEFORE the vmap stacking
  transpose, and dead-sender erasure uses `where` on the i32 slots.

Packed wire (DESIGN.md §13): the HBM wire form is further shrunk by
four cfg LAYOUT dials — bit-packed bool lanes (`pack_bools`), 16-bit
delta-encoded ring terms (`pack_ring`), input/output-aliased + donated
buffers (`alias_wire`), and histogram-row opt-out (`wire_hist`). The
encode/decode happens ONLY at chunk boundaries (`_pack_wire` /
`_unpack_wire`, shared host/kernel), so everything above this
paragraph — the tick, the metrics fold, the bit-identity contract —
is layout-blind; with every dial off the wire is byte-identical to
pre-r13. `_wire_state_leaves` is the packed-layout registry every
byte model derives from.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.clients.state import ClientState, active_client_leaves
from raft_tpu.clients import workload as _workload
from raft_tpu.config import (CONFIG_FLAG, SESSION_FLAG, SESSION_SEQ_MASK,
                             SESSION_SEQ_SHIFT, SESSION_SID_MASK,
                             SESSION_SID_SHIFT, RaftConfig)
from raft_tpu.core.node import (CANDIDATE, FOLLOWER, LEADER,
                                NO_VOTE, PRECANDIDATE)
from raft_tpu.obs.recorder import FLIGHT_LEAVES, PRESENCE_FIELDS, Flight
from raft_tpu.obs.recorder import RING as FLIGHT_RING
from raft_tpu.sim.run import HIST_SIZE, Metrics
from raft_tpu.sim.state import BOOL, I32, Mailbox, PerNode, State
from raft_tpu.utils import jrng

LANE = 128   # lane width: trailing dim of every k-state leaf
SUB = 8      # sublanes per block (min: block sublane dim must be 8-divisible)
GB = SUB * LANE   # groups per block (1024): ~5 MB of VMEM state/block
VMEM_LIMIT_BYTES = 100 * 1024 * 1024   # budget passed to the compiler
# Per-chip HBM budget for the wire-form model (supported()/hbm_bytes).
# Defaults to the TPU v5 lite's 16 GiB; a driver on a larger-HBM part
# (v4: 32 GB, v5p: 95 GB) raises it via $RAFT_TPU_HBM_BYTES rather
# than this module probing device memory_stats itself — on this image
# touching the TPU plugin from a CPU process can hang (conftest.py).
# Read ONCE at import (a constant, like the VMEM budget): set the env
# var before the first raft_tpu import; mutating os.environ afterwards
# has no effect.
import os as _os
HBM_LIMIT_BYTES = int(_os.environ.get("RAFT_TPU_HBM_BYTES",
                                      16 * 1024 ** 3))
# Host-RAM budget for the STREAMED residency model (DESIGN.md §15):
# under cfg.stream_groups the full fleet's wire form lives in host RAM
# and only O(cohort_blocks) 1024-group blocks are HBM-resident, so the
# group ceiling is bounded by this figure, not by HBM. Defaults to
# 64 GiB — conservative for a TPU host VM (v4/v5 hosts carry hundreds
# of GB); a driver on a bigger/smaller host sets $RAFT_TPU_HOST_RAM_BYTES.
# Read ONCE at import, exactly like the HBM budget above.
HOST_RAM_LIMIT_BYTES = int(_os.environ.get("RAFT_TPU_HOST_RAM_BYTES",
                                           64 * 1024 ** 3))


def _kind_words(cfg: RaftConfig, kind: str) -> int:
    return {"scalar": 1, "peer": cfg.k, "ring": cfg.log_cap,
            "sess": cfg.client_slots}[kind]


# Names of the SYNTHETIC wire leaves the packed layout introduces —
# shared with analysis/bytemodel.py's report rows and the ablation
# probe so every surface names the packed lanes identically.
MB_BOOLS_PACKED = "mailbox[bools packed]"
RING_BASE = "log_term[ring base]"


def _wire_state_leaves(cfg: RaftConfig) -> list:
    """(name, i32 words/group) per wire leaf of the STATE section, in
    wire order — THE packed-layout registry (DESIGN.md §13). With every
    layout dial off this is exactly the r12 wire: node leaves, mailbox
    leaves, client-state leaves, alive_prev, group_id, one i32 word per
    element. The dials rewrite entries in place:

    - pack_bools: `votes` packs its peer axis into per-node bit lanes
      (k*k -> k words); ALL bool mailbox leaves collapse into one
      shared-lane leaf at the first bool field's position (bit =
      field x src, ceil(n_bool * k / 32) words per dst); alive_prev
      packs its node axis (k -> 1 word).
    - pack_ring: `log_term` carries 16-bit deltas two-per-word
      (k*L -> k*L/2) plus one per-group base lane (bit 31 = the sticky
      delta-overflow flag kfinish refuses on).
    """
    out = []
    mbb = set(_mb_bool_fields(cfg)) if cfg.pack_bools else set()
    for f, kind in _node_leaves(cfg):
        if cfg.pack_bools and f == "votes":
            out.append(("votes", cfg.k))
        elif cfg.pack_ring and f == "log_term":
            out.append(("log_term", cfg.k * cfg.log_cap // 2))
            out.append((RING_BASE, 1))
        else:
            out.append((f, cfg.k * _kind_words(cfg, kind)))
    packed_emitted = False
    for f in _mb_fields(cfg):
        if f in mbb:
            if not packed_emitted:
                w = -(-len(mbb) * cfg.k // 32)   # words per dst node
                out.append((MB_BOOLS_PACKED, w * cfg.k))
                packed_emitted = True
            continue
        out.append((f, cfg.k * cfg.k * (cfg.client_slots
                                        if f == "is_req_snap_sessions"
                                        else 1)))
    if cfg.clients_u32:
        out.extend((f, cfg.client_slots) for f in active_client_leaves(cfg))
    out.append(("alive_prev", 1 if cfg.pack_bools else cfg.k))
    out.append(("group_id", 1))
    return out


def _wire_index(cfg: RaftConfig, name: str) -> int:
    """Position of a named leaf in the wire tuple's state section —
    the packed layout inserts/removes leaves, so host-side readers
    (kreads) index by NAME, never by a registry-order constant."""
    return [n for n, _ in _wire_state_leaves(cfg)].index(name)


def _state_words_per_group(cfg: RaftConfig) -> int:
    """i32 words per group of the NON-ROW wire leaves: the packed-
    layout registry's state section (node + mailbox + client leaves,
    alive_prev, group_id — packed per the cfg dials) plus the per-group
    metric lanes (every active metric leaf except the [H]-row
    histograms). The one accumulation both byte predicates share —
    the VMEM and HBM models drifted apart once (alive_prev counted as
    1 word in one copy); tests pin this form against real kinit
    leaves, packing off AND on."""
    words = sum(w for _, w in _wire_state_leaves(cfg))
    scalar_lanes = len(_active_metric_leaves(cfg)) - _n_row_metrics(cfg)
    return words + scalar_lanes


def _vmem_state_words(cfg: RaftConfig) -> int:
    """i32 words per group of the UNPACKED in-kernel live form (bools
    widened, rings full-width — what the fori_loop actually carries in
    VMEM regardless of the wire dials). Equals the wire accounting with
    every packing dial off."""
    words = 0
    for _, kind in _node_leaves(cfg):
        words += cfg.k * _kind_words(cfg, kind)
    for f in _mb_fields(cfg):
        words += cfg.k * cfg.k * (cfg.client_slots
                                  if f == "is_req_snap_sessions" else 1)
    if cfg.clients_u32:
        words += len(active_client_leaves(cfg)) * cfg.client_slots
    scalar_lanes = len(_active_metric_leaves(cfg)) - _n_row_metrics(cfg)
    return words + cfg.k + 1 + scalar_lanes


def kernel_vmem_bytes(cfg: RaftConfig) -> int:
    """Estimated peak VMEM bytes one grid step needs under `cfg`.

    Counts the i32 words of one 1024-group block's wire leaves (node
    state + mailbox + client state + alive/gid + metric tiles +
    histogram rows), then multiplies by 5: an input and an output
    buffer per leaf, the pipeline double-buffering both, plus roughly
    one block's worth held live in the fori_loop carry/vregs. A coarse
    model — it only has to reject shapes that would OOM the 100 MB
    budget by integer factors (huge L or K), not referee marginal
    fits."""
    # hist rows + the flight-recorder rows (reserved whether or not the
    # caller passes a flight — the predicate must not flip per call).
    block = (_vmem_state_words(cfg) * 4 * GB
             + _n_row_metrics(cfg) * HIST_SIZE * 4 * SUB * LANE
             + len(FLIGHT_LEAVES) * FLIGHT_RING * 4 * SUB * LANE)
    return 5 * block


def wire_words_per_group(cfg: RaftConfig, with_flight: bool = True) -> int:
    """i32 words per group of the kernel wire form: node + mailbox +
    client-state leaves, alive_prev + group_id (each packed per the cfg
    layout dials — `_wire_state_leaves`), the per-group metric lanes
    INCLUDING the [H]-row in-kernel histogram(s) when `cfg.wire_hist`
    (two with clients on: election latency + client ack latency), and
    (by default — `kinit` reserves the predicate for it whether or not
    a flight rides) the six flight-recorder ring rows. This is the HBM
    cost model the mesh-aware `supported()` and
    `scripts/layout_probe.py` share; note the histograms (HIST_SIZE
    words each) and flight rings (6 x RING words) are per-GROUP on the
    wire, unlike the XLA path's global [H] histograms — the biggest
    non-state contributors to the G ceiling (DESIGN.md §9/§10), which
    is why both are dials now (§13)."""
    words = _state_words_per_group(cfg) + _n_row_metrics(cfg) * HIST_SIZE
    if with_flight:
        words += len(FLIGHT_LEAVES) * FLIGHT_RING
    return words


def _residency_buffers(cfg: RaftConfig) -> int:
    """Live wire copies across a kernel launch: 2 (pallas allocates
    fresh outputs, so an input AND an output copy of every leaf exist)
    or 1 under `cfg.alias_wire` (input/output aliasing donates the
    input buffers — DESIGN.md §13)."""
    return 1 if cfg.alias_wire else 2


def hbm_bytes(cfg: RaftConfig, n_groups: int, n_devices: int = 1,
              with_flight: bool = True) -> int:
    """Peak per-device HBM bytes a sharded kernel run needs: the
    per-device group count padded to a whole block, times the wire
    words, times the residency multiplier — 2 without donation (an
    input and an output copy of every leaf are live across a launch),
    1 under `cfg.alias_wire` (the pallas_call aliases every wire input
    to its output and the jit donates the operands). `with_flight=
    False` models a run without the flight-recorder ring (the ring
    leaves exist on the wire only when kinit gets one)."""
    padded = (-(-n_groups // (n_devices * GB))) * GB
    return (_residency_buffers(cfg) * 4
            * wire_words_per_group(cfg, with_flight) * padded)


def hbm_ceiling_groups(cfg: RaftConfig, n_devices: int = 1,
                       with_flight: bool = True) -> int:
    """Largest group count `supported(..., with_flight=...)` admits on
    `n_devices`: whole 1024-group blocks only, consistent with
    `hbm_bytes`'s padding — an unpadded HBM / bytes-per-group division
    over-promises by up to a block, and a sweep sized at that figure
    would be rejected by the very predicate that published it. Follows
    every cfg layout dial (packing, aliasing, wire_hist). The single
    source for every printed/emitted ceiling (layout_probe,
    multichip_sweep)."""
    per_block = (_residency_buffers(cfg) * 4
                 * wire_words_per_group(cfg, with_flight) * GB)
    return (HBM_LIMIT_BYTES // per_block) * GB * n_devices


def _stream_windows(cfg: RaftConfig) -> int:
    """Peak HBM-resident cohort windows of the double-buffered pipeline
    (parallel/cohort.py): the PREVIOUS cohort awaiting its HBM->host
    copy, the CURRENT one under the kernel (x residency buffers — in
    AND out copies live across a launch unless alias_wire donates), and
    the NEXT one prefetched host->HBM."""
    return 2 + _residency_buffers(cfg)


def host_bytes(cfg: RaftConfig, n_groups: int,
               with_flight: bool = True) -> int:
    """Host-RAM bytes a streamed run pins: ONE copy of the full fleet's
    wire form, padded to whole 1024-group blocks (kinit's padding rule
    — the host arrays ARE kinit's leaves, fetched once)."""
    padded = (-(-n_groups // GB)) * GB
    return 4 * wire_words_per_group(cfg, with_flight) * padded


def stream_blocks_per_device(cfg: RaftConfig, n_devices: int = 1) -> int:
    """Whole 1024-group blocks of one cohort window that land on EACH
    device: `cohort_blocks` split over the mesh, rounded UP so every
    per-device window slice is a whole number of kernel blocks (the
    r17 sharded scheduler's global window is this figure x n_devices —
    at n_devices=1 it is exactly `cfg.cohort_blocks`)."""
    return -(-cfg.cohort_blocks // n_devices)


def cohort_hbm_bytes(cfg: RaftConfig, with_flight: bool = True,
                     n_devices: int = 1) -> int:
    """Peak per-device HBM bytes the streamed pipeline holds: the
    PER-DEVICE window slice (`stream_blocks_per_device` whole blocks —
    the full cohort window at n_devices=1, cohort_blocks/N rounded up
    under the r17 sharded scheduler) times the pipeline's live-window
    count (`_stream_windows`) — O(cohort_blocks), never O(G). This
    replaces `hbm_bytes` as the HBM side of `supported()` under
    cfg.stream_groups."""
    window = stream_blocks_per_device(cfg, n_devices) * GB
    return (_stream_windows(cfg) * 4
            * wire_words_per_group(cfg, with_flight) * window)


def streamed_ceiling_groups(cfg: RaftConfig, n_devices: int = 1,
                            with_flight: bool = True) -> int:
    """Largest group count `supported()` admits under cfg.stream_groups
    on `n_devices`: host-RAM-bound (ONE wire copy per group in host
    RAM, a PER-DEVICE allocation — the multi-host/pod model where each
    chip's host slice carries $RAFT_TPU_HOST_RAM_BYTES, matching
    `supported()`'s ceil(G / n_devices) budget), in whole 1024-group
    blocks, consistent with `host_bytes`'s padding — same
    exact-boundary contract as `hbm_ceiling_groups`, budget
    $RAFT_TPU_HOST_RAM_BYTES instead of $RAFT_TPU_HBM_BYTES. The
    PER-DEVICE cohort window must also fit HBM (`cohort_hbm_bytes` at
    `n_devices`) or no group count is admitted at all. The single
    source for every printed/emitted streamed ceiling (layout_probe,
    multichip_sweep, analysis/bytemodel)."""
    if cohort_hbm_bytes(cfg, with_flight, n_devices) > HBM_LIMIT_BYTES:
        return 0
    per_block = 4 * wire_words_per_group(cfg, with_flight) * GB
    return (HOST_RAM_LIMIT_BYTES // per_block) * GB * n_devices


def supported(cfg: RaftConfig, n_groups: int | None = None,
              n_devices: int = 1, with_flight: bool = True) -> bool:
    """Every batched-path feature is in-kernel: fault classes,
    scheduled reads, membership change, PreVote, leadership transfer,
    and the election-latency histogram — each statically gated exactly
    like step.py, pinned bit-identical by tests/test_pkernel.py.

    What the predicate actually rejects: voter bitmasks live in i32
    lanes (k <= 30 so `1 << k` and the config SWAR popcount stay exact),
    and the per-block VMEM footprint must fit the compiler budget —
    a [K, L] shape big enough to blow it (e.g. L in the thousands)
    needs the XLA path, which streams through HBM instead.

    Mesh-aware form: pass `n_groups` (and the device count the caller
    will shard over) and the predicate also requires the per-device
    wire-form footprint to fit HBM (`hbm_bytes`) — this is what turns
    "1M groups on one chip" from a Mosaic OOM into a clean False, and
    what the multichip sweep uses to mark unsupported grid cells.
    `with_flight=False` budgets a flight-ring-less run (prun passes
    the actual flight argument through); the budget itself defaults to
    16 GiB and follows $RAFT_TPU_HBM_BYTES on larger-HBM parts.

    Under `cfg.stream_groups` (DESIGN.md §15) the HBM side of the
    predicate changes residency scheme: only the cohort window
    (`cohort_hbm_bytes`, O(cohort_blocks)) must fit HBM, and `n_groups`
    is instead budgeted against host RAM (`host_bytes` per device vs
    $RAFT_TPU_HOST_RAM_BYTES) — the ceiling `streamed_ceiling_groups`
    publishes is the exact boundary of this branch."""
    if not (cfg.k <= 30 and kernel_vmem_bytes(cfg) <= VMEM_LIMIT_BYTES):
        return False
    if cfg.stream_groups:
        # Streamed residency (DESIGN.md §15/§16): the PER-DEVICE cohort
        # window must fit HBM whatever G is; G itself is bounded by
        # host RAM (one wire copy of each device's padded shard on its
        # host slice), not by HBM.
        if cohort_hbm_bytes(cfg, with_flight, n_devices) > HBM_LIMIT_BYTES:
            return False
        if n_groups is None:
            return True
        return (host_bytes(cfg, -(-n_groups // n_devices), with_flight)
                <= HOST_RAM_LIMIT_BYTES)
    if n_groups is None:
        return True
    return hbm_bytes(cfg, n_groups, n_devices, with_flight) \
        <= HBM_LIMIT_BYTES


# ----------------------------------------------------------- small helpers


def _col(n: int):
    """i32 [n, 1, 1] iota for one-hot masks over a leading axis."""
    return jax.lax.broadcasted_iota(I32, (n, 1, 1), 0)


def _lget(arr, idx):
    """arr[idx] over the leading axis: [N,8,128],[8,128] -> [8,128].

    Bit-tree select, NOT the XLA path's one-hot reduce: selecting by
    the bits of `idx` costs N-1 selects + log2(N) bit tests (~36 vreg
    ops at N=32) where compare+select+sum costs ~3N (~95). At a
    non-power-of-two N, an unpaired row pairs with itself, so
    out-of-range high bits of idx resolve to SOME in-range row —
    unreachable anyway, since callers guarantee 0 <= idx < N. i32 only
    (vector-bool selects do not lower; module docstring)."""
    rows = [arr[j] for j in range(arr.shape[0])]
    nbits = max(1, (arr.shape[0] - 1).bit_length())
    for b in range(nbits):
        bit = ((idx >> b) & 1) == 1
        rows = [jnp.where(bit, rows[j + 1] if j + 1 < len(rows) else rows[j],
                          rows[j])
                for j in range(0, len(rows), 2)]
    return rows[0]


def _lset(arr, idx, cond, val):
    """Masked arr[idx] = val over the leading axis via one-hot select."""
    return jnp.where((_col(arr.shape[0]) == idx) & cond, val, arr)


def _put(arr, p: int, cond, val):
    """Masked write of row p (static): the kernel's `step._put`. Bool
    rows use and/or masking with literal True/False short-circuited,
    keeping vector i1 constants out of the program (module docstring).
    The row-select iota matches `arr`'s rank (ndim-4 for the [K, S]
    session-table mailbox leaf, ndim-3 for everything else)."""
    m = (jax.lax.broadcasted_iota(
        I32, (arr.shape[0],) + (1,) * (arr.ndim - 1), 0) == p) & cond
    if arr.dtype == jnp.bool_:
        if val is True:
            return arr | m
        if val is False:
            return arr & ~m
        return (arr & ~m) | (m & val)
    return jnp.where(m, val, arr)


def _krow_or(arr, j: int, cond):
    """arr[j] |= cond (bool [K,8,128] row update, static j)."""
    return arr | ((_col(arr.shape[0]) == j) & cond)


def _slot(cfg: RaftConfig, idx):
    return (idx - 1) % cfg.log_cap


def _term_at(cfg, ns: PerNode, idx):
    return jnp.where(idx == ns.snap_index, ns.snap_term,
                     _lget(ns.log_term, _slot(cfg, idx)))


def _payload_at(cfg, ns: PerNode, idx):
    return _lget(ns.log_payload, _slot(cfg, idx))


def _last_log_term(cfg, ns: PerNode):
    return _term_at(cfg, ns, ns.last_index)


def _abs_index(cfg, ns: PerNode):
    """step._abs_index: [L, 8, 128] absolute index per ring slot."""
    off = _col(cfg.log_cap) - ns.snap_index % cfg.log_cap
    return ns.snap_index + 1 + jnp.where(off >= 0, off, off + cfg.log_cap)


def _vote_count(votes):
    """ops.quorum.vote_count over the leading K axis."""
    return jnp.sum(votes.astype(I32), axis=0)


def _commit_candidate(cfg, match_index, last_index, i):
    """ops.quorum.commit_candidate as a static compare-exchange network
    (jnp.sort has no Mosaic lowering). Peer values with the self slot
    forced to -1, sorted descending; element majority-2 is the
    candidate."""
    if cfg.majority == 1:
        return last_index
    rows = [jnp.where(jnp.int32(j) == i, jnp.int32(-1), match_index[j])
            for j in range(cfg.k)]
    for a in range(cfg.k):          # selection-sort network, descending
        for b in range(a + 1, cfg.k):
            hi = jnp.maximum(rows[a], rows[b])
            lo = jnp.minimum(rows[a], rows[b])
            rows[a], rows[b] = hi, lo
    return rows[cfg.majority - 2]


# ------------------------------------------------------- membership config
# Ports of step.py's derived-config helpers. Traced bit positions go
# through K-term one-hot sums (static shift amounts only).


def _popcount(x):
    """Set bits of an i32 mask (SWAR; k <= 30 bits)."""
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    return (x * 0x01010101) >> 24


def _voter_majority(voters):
    return _popcount(voters) // 2 + 1


def _bit_at(voters, i, k: int):
    """(voters >> i) & 1 for a TRACED i, as a static one-hot sum."""
    out = voters & 0
    for j in range(k):
        out = out + jnp.where(i == j, (voters >> j) & 1, 0)
    return out


def _onehot_mask(target, k: int):
    """1 << target for a TRACED target, as a static one-hot sum."""
    out = None
    for j in range(k):
        term = jnp.where(target == j, jnp.int32(1 << j), 0)
        out = term if out is None else out + term
    return out


def _config_scan(cfg, ns: PerNode, through):
    """step._config_scan: latest CONFIG_FLAG entry <= `through` in the
    live window, else the snapshot's config."""
    absidx = _abs_index(cfg, ns)
    is_cfg = (((ns.log_payload & CONFIG_FLAG) != 0)
              & (absidx <= jnp.minimum(ns.last_index, through)))
    best = jnp.max(jnp.where(is_cfg, absidx, 0), axis=0)   # 0 == none
    found = best > 0
    mask_at = jnp.sum(
        jnp.where(is_cfg & (absidx == best), ns.log_payload, 0),
        axis=0) & cfg.full_mask
    return (jnp.where(found, mask_at, ns.snap_voters),
            jnp.where(found, best, ns.snap_index))


def _current_config(cfg, ns: PerNode):
    if cfg.reconfig_u32 == 0:          # static fast path (step.py)
        return jnp.int32(cfg.full_mask), ns.snap_index
    return _config_scan(cfg, ns, jnp.int32(0x7FFFFFFF))


def _committed_voters(cfg, ns: PerNode, commit):
    if cfg.reconfig_u32 == 0:
        return jnp.int32(cfg.full_mask)
    return _config_scan(cfg, ns, commit)[0]


def _vote_quorum(cfg, ns: PerNode, votes):
    """step._vote_quorum: granted votes from CURRENT-config voters reach
    that config's majority."""
    if cfg.reconfig_u32 == 0:
        return _vote_count(votes) >= cfg.majority
    voters, _ = _current_config(cfg, ns)
    granted = None
    for j in range(cfg.k):
        term = (votes[j] & (((voters >> j) & 1) == 1)).astype(I32)
        granted = term if granted is None else granted + term
    return granted >= _voter_majority(voters)


# -------------------------------------------------------------- transitions
# Ports of step.py's masked transition helpers (same names, same order
# of field writes). `g` is the [8, 128] group-id tile; `i` the node's
# id ([1, 1] tile under the node vmap).


def _reset_timer(cfg, ns: PerNode, g, i, cond, t):
    deadline = jrng.election_deadline(cfg.seed, g, i, ns.rng_draws,
                                      cfg.election_min, cfg.election_range)
    if cfg.nem_skew:
        # Nemesis clock-skew clauses (DESIGN.md §14; step._reset_timer).
        deadline = jnp.maximum(1, deadline + jrng.nem_deadline_extra(
            cfg.seed, cfg.nem_skew, g, i, t))
    return ns._replace(
        election_elapsed=jnp.where(cond, 0, ns.election_elapsed),
        deadline=jnp.where(cond, deadline, ns.deadline),
        rng_draws=ns.rng_draws + cond.astype(I32),
    )


def _drop_reads(cfg, ns: PerNode, cond):
    """step._drop_reads: statically absent when the schedule is off."""
    if not cfg.read_every:
        return ns
    return ns._replace(
        ack_time=jnp.where(cond, -1, ns.ack_time),
        sched_read_index=jnp.where(cond, -1, ns.sched_read_index),
    )


def _step_down(cfg, ns: PerNode, new_term, cond):
    ns = ns._replace(
        term=jnp.where(cond, new_term, ns.term),
        role=jnp.where(cond, FOLLOWER, ns.role),
        voted_for=jnp.where(cond, NO_VOTE, ns.voted_for),
        leader_id=jnp.where(cond, NO_VOTE, ns.leader_id),
        votes=ns.votes & ~cond,
    )
    return _drop_reads(cfg, ns, cond)


def _become_leader(cfg, ns: PerNode, i, cond):
    ns = _drop_reads(cfg, ns, cond)
    ns = ns._replace(
        role=jnp.where(cond, LEADER, ns.role),
        leader_id=jnp.where(cond, i, ns.leader_id),
        next_index=jnp.where(cond, ns.last_index + 1, ns.next_index),
        match_index=jnp.where(cond, 0, ns.match_index),
        heartbeat_elapsed=jnp.where(cond, cfg.heartbeat_every,
                                    ns.heartbeat_elapsed),
    )
    top = cond & (ns.last_index > ns.commit)
    return ns._replace(
        log_term=_lset(ns.log_term, _slot(cfg, ns.last_index), top, ns.term))


def _accept_leader(cfg, ns: PerNode, g, i, src: int, cond, t):
    ns = ns._replace(
        role=jnp.where(cond, FOLLOWER, ns.role),
        leader_id=jnp.where(cond, src, ns.leader_id),
        votes=ns.votes & ~cond,
        leader_elapsed=jnp.where(cond, 0, ns.leader_elapsed),
    )
    return _reset_timer(cfg, ns, g, i, cond, t)


# ----------------------------------------------------------------- phase D


def _on_rv_req(cfg, ns, out, g, i, src: int, ib, gl):
    present = ib.rv_req_present[src]
    m_term = ib.rv_req_term[src]
    m_lli = ib.rv_req_lli[src]
    m_llt = ib.rv_req_llt[src]
    ns = _step_down(cfg, ns, m_term, present & (m_term > ns.term))
    llt = _last_log_term(cfg, ns)
    log_ok = (m_llt > llt) | ((m_llt == llt) & (m_lli >= ns.last_index))
    grant = (present & (m_term == ns.term)
             & ((ns.voted_for == NO_VOTE) | (ns.voted_for == src))
             & log_ok)
    ns = ns._replace(voted_for=jnp.where(grant, src, ns.voted_for))
    ns = _reset_timer(cfg, ns, g, i, grant, gl[2])
    out = out._replace(
        rv_resp_present=_put(out.rv_resp_present, src, present, True),
        rv_resp_term=_put(out.rv_resp_term, src, present, ns.term),
        rv_resp_granted=_put(out.rv_resp_granted, src, present, grant),
    )
    return ns, out


def _on_rv_resp(cfg, ns, out, g, i, src: int, ib, gl):
    present = ib.rv_resp_present[src]
    m_term = ib.rv_resp_term[src]
    m_granted = ib.rv_resp_granted[src]
    higher = present & (m_term > ns.term)
    ns = _step_down(cfg, ns, m_term, higher)
    cont = (present & ~higher & (ns.role == CANDIDATE)
            & (m_term == ns.term) & m_granted)
    votes = _krow_or(ns.votes, src, cont)
    ns = ns._replace(votes=votes)
    won = cont & _vote_quorum(cfg, ns, votes)
    return _become_leader(cfg, ns, i, won), out


def _on_ae_req(cfg, ns, out, g, i, src: int, ib, gl):
    """step._on_ae_req, fused (module docstring / DESIGN.md §7b):
    receiver-pull log matching with the four per-sender read passes
    collapsed into one packed ring compare, decide-then-write. Ring
    reads stay per-sender against the CURRENT log (a later same-tick
    AE must see an earlier one's writes); only `_abs_index` and its
    live-window mask arrive hoisted via `gl`."""
    glog_t, glog_p = gl[0], gl[1]
    absidx, live = gl[3], gl[4]     # hoisted once per tick (_node_tick)
    present = ib.ae_req_present[src]
    m_term = ib.ae_req_term[src]
    m_prev = ib.ae_req_prev_index[src]
    m_prev_term = ib.ae_req_prev_term[src]
    m_n = ib.ae_req_n[src]
    m_commit = ib.ae_req_commit[src]

    ns = _step_down(cfg, ns, m_term, present & (m_term > ns.term))
    stale = present & (m_term < ns.term)
    ok = present & ~stale
    ns = _accept_leader(cfg, ns, g, i, src, ok, gl[2])

    past = ok & (m_prev > ns.last_index)
    ct = _term_at(cfg, ns, m_prev)
    conflict = (ok & ~past & (m_prev >= ns.snap_index)
                & (ct != m_prev_term))
    bad = live & (absidx < m_prev) & (ns.log_term != ct)
    ci = jnp.minimum(
        jnp.max(jnp.where(bad, absidx, ns.snap_index), axis=0) + 1, m_prev)

    proceed = ok & ~past & ~conflict
    j0 = jnp.maximum(0, ns.snap_index - m_prev)
    # ONE masked pass over the paired rings replaces the 2E sender
    # pulls + 2E own-ring reads: the same absolute index lives at the
    # same slot on both nodes, so per-slot equality of the two rings is
    # everything the entry walk needs — bit 0 = terms equal, bit 1 =
    # payloads equal (packed i32: vector-bool selects do not lower).
    cmp = ((ns.log_term == glog_t[src]).astype(I32)
           | ((ns.log_payload == glog_p[src]).astype(I32) << 1))
    hi = m_prev + j0
    last_index = ns.last_index
    stopped = proceed & (g < 0)                 # all-false, constant-free
    # Storage pressure (r20, DESIGN.md §19), mirroring step._on_ae_req:
    # a disk-full node's appends fail — `hi` stops at the durable
    # prefix (the partial-ack NACK), while matching entries, in-place
    # term rewrites and divergent-suffix truncation stay live. The
    # mask is pure hash compares on runtime coordinates (Mosaic-legal;
    # statically absent with no disk clause).
    df = None
    if cfg.nem_disk:
        df = jrng.nem_disk_full(cfg.seed, cfg.nem_disk, g, i,
                                gl[2], cfg.k)
    write_t, write_p, slots = [], [], []
    for j in range(cfg.max_entries_per_msg):
        idx = m_prev + 1 + j
        act = proceed & (j >= j0) & (j < m_n) & ~stopped
        s = _slot(cfg, idx)
        slots.append(s)
        cj = _lget(cmp, s)
        in_log = act & (idx <= last_index)
        same_t = in_log & ((cj & 1) != 0)
        same_p = in_log & ~same_t & ((cj & 2) != 0)
        diverge = in_log & ~same_t & ~same_p
        need_append = (act & ~in_log) | diverge
        room = (idx - ns.snap_index) <= cfg.log_cap
        if df is not None:
            room = room & ~df
        do_append = need_append & room
        write_t.append(same_p | do_append)
        write_p.append(do_append)
        last_index = jnp.where(
            do_append, idx,
            jnp.where(diverge & ~room, idx - 1, last_index))
        stopped = stopped | (need_append & ~room)
        hi = jnp.where(same_t | same_p | do_append, idx, hi)
    lanes = _col(cfg.log_cap)
    t_mask = jnp.broadcast_to(g, (cfg.log_cap,) + g.shape) < 0  # all-false
    p_mask = t_mask
    for j in range(cfg.max_entries_per_msg):
        on_j = lanes == slots[j]
        t_mask = t_mask | (on_j & write_t[j])
        p_mask = p_mask | (on_j & write_p[j])
    # Write VALUES are the sender's ring itself (same-slot identity):
    # no per-entry value composition to materialize.
    log_term = jnp.where(t_mask, glog_t[src], ns.log_term)
    log_payload = jnp.where(p_mask, glog_p[src], ns.log_payload)

    commit = jnp.where(
        proceed & (m_commit > ns.commit),
        jnp.maximum(ns.commit, jnp.minimum(m_commit, hi)),
        ns.commit)
    ns = ns._replace(log_term=log_term, log_payload=log_payload,
                     last_index=last_index, commit=commit)

    match = jnp.where(
        past, last_index + 1,
        jnp.where(conflict, ci, jnp.where(proceed, hi, 0)))
    out = out._replace(
        ae_resp_present=_put(out.ae_resp_present, src, present, True),
        ae_resp_term=_put(out.ae_resp_term, src, present, ns.term),
        ae_resp_success=_put(out.ae_resp_success, src, present, proceed),
        ae_resp_match=_put(out.ae_resp_match, src, present, match),
    )
    return ns, out


def _on_ae_resp(cfg, ns, out, g, i, src: int, ib, gl):
    present = ib.ae_resp_present[src]
    m_term = ib.ae_resp_term[src]
    m_success = ib.ae_resp_success[src]
    m_match = ib.ae_resp_match[src]
    higher = present & (m_term > ns.term)
    ns = _step_down(cfg, ns, m_term, higher)
    cont = present & ~higher & (ns.role == LEADER) & (m_term == ns.term)
    if cfg.read_every:
        # Any current-term response is ReadIndex deference evidence
        # (step.py:379): stamp the arrival tick, success or not.
        ns = ns._replace(ack_time=jnp.where(
            (_col(cfg.k) == src) & cont, gl[2], ns.ack_time))
    succ = cont & m_success
    fail = cont & ~m_success
    old_match = ns.match_index[src]
    old_next = ns.next_index[src]
    new_match = jnp.maximum(old_match, m_match)
    kio = _col(cfg.k)
    match_index = jnp.where((kio == src) & succ, new_match, ns.match_index)
    next_index = jnp.where(
        kio == src,
        jnp.where(succ, new_match + 1,
                  jnp.where(fail,
                            jnp.maximum(1, jnp.minimum(old_next - 1, m_match)),
                            old_next)),
        ns.next_index)
    return ns._replace(match_index=match_index, next_index=next_index), out


def _on_is_req(cfg, ns, out, g, i, src: int, ib, gl):
    present = ib.is_req_present[src]
    m_term = ib.is_req_term[src]
    m_si = ib.is_req_snap_index[src]
    m_st = ib.is_req_snap_term[src]
    m_sd = ib.is_req_snap_digest[src]
    m_sv = ib.is_req_snap_voters[src]
    ns = _step_down(cfg, ns, m_term, present & (m_term > ns.term))
    stale = present & (m_term < ns.term)
    ok = present & ~stale
    ns = _accept_leader(cfg, ns, g, i, src, ok, gl[2])
    have = ok & (m_si <= ns.commit)
    inst = ok & ~have
    keep = (inst & (m_si <= ns.last_index) & (m_si >= ns.snap_index)
            & (_term_at(cfg, ns, jnp.maximum(m_si, ns.snap_index)) == m_st))
    sess = {}
    if cfg.clients_u32:
        # step._on_is_req: the snapshot's dedup table installs by value.
        m_sess = ib.is_req_snap_sessions[src]
        sess = dict(session_seq=jnp.where(inst, m_sess, ns.session_seq),
                    snap_session_seq=jnp.where(inst, m_sess,
                                               ns.snap_session_seq))
    ns = ns._replace(
        last_index=jnp.where(inst, jnp.where(keep, ns.last_index, m_si),
                             ns.last_index),
        snap_index=jnp.where(inst, m_si, ns.snap_index),
        snap_term=jnp.where(inst, m_st, ns.snap_term),
        snap_digest=jnp.where(inst, m_sd, ns.snap_digest),
        snap_voters=jnp.where(inst, m_sv, ns.snap_voters),
        commit=jnp.where(inst, m_si, ns.commit),
        applied=jnp.where(inst, m_si, ns.applied),
        digest=jnp.where(inst, m_sd, ns.digest),
        **sess,
    )
    match = jnp.where(stale, 0, jnp.where(have, ns.commit, m_si))
    out = out._replace(
        is_resp_present=_put(out.is_resp_present, src, present, True),
        is_resp_term=_put(out.is_resp_term, src, present, ns.term),
        is_resp_match=_put(out.is_resp_match, src, present, match),
    )
    return ns, out


def _on_is_resp(cfg, ns, out, g, i, src: int, ib, gl):
    present = ib.is_resp_present[src]
    m_term = ib.is_resp_term[src]
    m_match = ib.is_resp_match[src]
    higher = present & (m_term > ns.term)
    ns = _step_down(cfg, ns, m_term, higher)
    cont = present & ~higher & (ns.role == LEADER) & (m_term == ns.term)
    if cfg.read_every:
        ns = ns._replace(ack_time=jnp.where(
            (_col(cfg.k) == src) & cont, gl[2], ns.ack_time))
    old_match = ns.match_index[src]
    new_match = jnp.maximum(old_match, m_match)
    kio = _col(cfg.k)
    match_index = jnp.where((kio == src) & cont, new_match, ns.match_index)
    next_index = jnp.where((kio == src) & cont, new_match + 1, ns.next_index)
    return ns._replace(match_index=match_index, next_index=next_index), out


def _start_election_masked(cfg, ns, out, g, i, cond, t):
    ns = ns._replace(
        term=jnp.where(cond, ns.term + 1, ns.term),
        role=jnp.where(cond, CANDIDATE, ns.role),
        voted_for=jnp.where(cond, i, ns.voted_for),
        leader_id=jnp.where(cond, NO_VOTE, ns.leader_id),
        votes=(ns.votes & ~cond) | (cond & (_col(cfg.k) == i)),
    )
    ns = _reset_timer(cfg, ns, g, i, cond, t)
    won = cond & _vote_quorum(cfg, ns, ns.votes)   # instant single-voter win
    ns = _become_leader(cfg, ns, i, won)
    llt = _last_log_term(cfg, ns)
    for p in range(cfg.k):
        send = cond & ~won & (i != p)
        out = out._replace(
            rv_req_present=_put(out.rv_req_present, p, send, True),
            rv_req_term=_put(out.rv_req_term, p, send, ns.term),
            rv_req_lli=_put(out.rv_req_lli, p, send, ns.last_index),
            rv_req_llt=_put(out.rv_req_llt, p, send, llt),
        )
    return ns, out


def _on_pv_req(cfg, ns, out, g, i, src: int, ib, gl):
    """step._on_pv_req: non-binding pre-vote grant — proposed term
    ahead, log up-to-date, not the leader, lease expired. No term
    adoption, no voted_for, no timer reset."""
    if not cfg.prevote:
        return ns, out
    present = ib.pv_req_present[src]
    m_term = ib.pv_req_term[src]
    m_lli = ib.pv_req_lli[src]
    m_llt = ib.pv_req_llt[src]
    llt = _last_log_term(cfg, ns)
    log_ok = (m_llt > llt) | ((m_llt == llt) & (m_lli >= ns.last_index))
    grant = (present & (m_term > ns.term) & log_ok & (ns.role != LEADER)
             & (ns.leader_elapsed >= cfg.election_min))
    out = out._replace(
        pv_resp_present=_put(out.pv_resp_present, src, present, True),
        pv_resp_term=_put(out.pv_resp_term, src, present, ns.term),
        pv_resp_req_term=_put(out.pv_resp_req_term, src, present, m_term),
        pv_resp_granted=_put(out.pv_resp_granted, src, present, grant),
    )
    return ns, out


def _on_pv_resp(cfg, ns, out, g, i, src: int, ib, gl):
    """step._on_pv_resp: tally pre-votes; a quorum starts the REAL
    election (term bump + RequestVote broadcast) right here in phase D."""
    if not cfg.prevote:
        return ns, out
    present = ib.pv_resp_present[src]
    m_term = ib.pv_resp_term[src]
    m_req = ib.pv_resp_req_term[src]
    m_granted = ib.pv_resp_granted[src]
    higher = present & (m_term > ns.term)
    ns = _step_down(cfg, ns, m_term, higher)
    cont = (present & ~higher & (ns.role == PRECANDIDATE)
            & (m_req == ns.term + 1) & m_granted)
    votes = _krow_or(ns.votes, src, cont)
    ns = ns._replace(votes=votes)
    won_pre = cont & _vote_quorum(cfg, ns, votes)
    return _start_election_masked(cfg, ns, out, g, i, won_pre, gl[2])


def _on_tn_req(cfg, ns, out, g, i, src: int, ib, gl):
    """step._on_tn_req: TimeoutNow — campaign immediately, bypassing
    PreVote. FOLLOWER/PRECANDIDATE only (a CANDIDATE already campaigned
    and a second start would double-write the RV slot)."""
    if not cfg.transfer_u32:
        return ns, out
    present = ib.tn_present[src]
    m_term = ib.tn_term[src]
    ns = _step_down(cfg, ns, m_term, present & (m_term > ns.term))
    cond = (present & (m_term == ns.term)
            & (ns.role != LEADER) & (ns.role != CANDIDATE))
    if cfg.reconfig_u32:
        voters, _ = _current_config(cfg, ns)
        cond = cond & (_bit_at(voters, i, cfg.k) == 1)
    return _start_election_masked(cfg, ns, out, g, i, cond, gl[2])


_HANDLERS = (_on_rv_req, _on_rv_resp, _on_ae_req, _on_ae_resp,
             _on_is_req, _on_is_resp, _on_pv_req, _on_pv_resp, _on_tn_req)
#             canonical rpc type order (PV/TN last — step.py/rpc.py)


# ------------------------------------------------------------- phases T/C/A


def _phase_t(cfg, ns, out, g, i, t):
    is_leader = ns.role == LEADER
    hb = ns.heartbeat_elapsed + 1
    fire = is_leader & (hb >= cfg.heartbeat_every)
    ns = ns._replace(heartbeat_elapsed=jnp.where(
        is_leader, jnp.where(fire, 0, hb), ns.heartbeat_elapsed))

    for p in range(cfg.k):
        cond = fire & (i != p)
        next_p = ns.next_index[p]
        use_is = cond & (next_p <= ns.snap_index)
        use_ae = cond & (next_p > ns.snap_index)
        out = out._replace(
            is_req_present=_put(out.is_req_present, p, use_is, True),
            is_req_term=_put(out.is_req_term, p, use_is, ns.term),
            is_req_snap_index=_put(out.is_req_snap_index, p, use_is,
                                   ns.snap_index),
            is_req_snap_term=_put(out.is_req_snap_term, p, use_is,
                                  ns.snap_term),
            is_req_snap_digest=_put(out.is_req_snap_digest, p, use_is,
                                    ns.snap_digest),
            is_req_snap_voters=_put(out.is_req_snap_voters, p, use_is,
                                    ns.snap_voters),
        )
        if cfg.clients_u32:
            out = out._replace(is_req_snap_sessions=_put(
                out.is_req_snap_sessions, p, use_is, ns.snap_session_seq))
        prev = next_p - 1
        n = jnp.minimum(cfg.max_entries_per_msg, ns.last_index - prev)
        out = out._replace(
            ae_req_present=_put(out.ae_req_present, p, use_ae, True),
            ae_req_term=_put(out.ae_req_term, p, use_ae, ns.term),
            ae_req_prev_index=_put(out.ae_req_prev_index, p, use_ae, prev),
            ae_req_prev_term=_put(out.ae_req_prev_term, p, use_ae,
                                  _term_at(cfg, ns, prev)),
            ae_req_n=_put(out.ae_req_n, p, use_ae, n),
            ae_req_commit=_put(out.ae_req_commit, p, use_ae, ns.commit),
        )

    if cfg.transfer_u32:
        # step._phase_t scheduled transfer: first tick of a firing
        # epoch, hash-chosen target, gated on current-config voter +
        # fully-caught-up peer (self match slot is always 0, so the max
        # ranges over peers only).
        epoch = t // cfg.transfer_epoch
        attempts = (is_leader & ((t % cfg.transfer_epoch) == 0)
                    & jrng.transfer_fires(cfg.seed, g, epoch,
                                          cfg.transfer_u32))
        target = jrng.transfer_target(cfg.seed, g, epoch, cfg.k)
        mt = _lget(ns.match_index, target)
        caught_up = ((mt >= ns.commit)
                     & (mt == jnp.max(ns.match_index, axis=0)))
        okT = attempts & caught_up & (target != i)
        if cfg.reconfig_u32:
            votersT, _ = _current_config(cfg, ns)
            okT = okT & (_bit_at(votersT, target, cfg.k) == 1)
        for p in range(cfg.k):
            send = okT & (target == p)
            out = out._replace(
                tn_present=_put(out.tn_present, p, send, True),
                tn_term=_put(out.tn_term, p, send, ns.term),
            )

    ee = ns.election_elapsed + 1
    timeout = ~is_leader & (ee >= ns.deadline)
    if cfg.reconfig_u32:
        # Non-voters never campaign (step.py:624-626).
        voters0, _ = _current_config(cfg, ns)
        timeout = timeout & (_bit_at(voters0, i, cfg.k) == 1)
    ns = ns._replace(
        election_elapsed=jnp.where(is_leader, ns.election_elapsed, ee),
        leader_elapsed=jnp.where(is_leader, 0, ns.leader_elapsed + 1))
    if cfg.prevote:
        # step._phase_t pre-ballot: pre-candidacy, no term bump; the
        # single-voter config skips straight to the real election
        # (matching the CPU's nested _start_election call, including
        # its second deadline draw).
        ns = ns._replace(
            role=jnp.where(timeout, PRECANDIDATE, ns.role),
            leader_id=jnp.where(timeout, NO_VOTE, ns.leader_id),
            votes=(ns.votes & ~timeout) | (timeout & (_col(cfg.k) == i)),
        )
        ns = _reset_timer(cfg, ns, g, i, timeout, t)
        skip = timeout & _vote_quorum(cfg, ns, ns.votes)
        ns, out = _start_election_masked(cfg, ns, out, g, i, skip, t)
        llt = _last_log_term(cfg, ns)
        for p in range(cfg.k):
            send = timeout & ~skip & (i != p)
            out = out._replace(
                pv_req_present=_put(out.pv_req_present, p, send, True),
                pv_req_term=_put(out.pv_req_term, p, send, ns.term + 1),
                pv_req_lli=_put(out.pv_req_lli, p, send, ns.last_index),
                pv_req_llt=_put(out.pv_req_llt, p, send, llt),
            )
        return ns, out
    return _start_election_masked(cfg, ns, out, g, i, timeout, t)


def _phase_c(cfg, ns, g, i, t, csub=None, cpay=None):
    lead = ns.role == LEADER
    # Disk-full leaders append nothing (r20) — step._phase_c's mask,
    # folded into every room check below.
    df = None
    if cfg.nem_disk:
        df = jrng.nem_disk_full(cfg.seed, cfg.nem_disk, g, i, t, cfg.k)

    if cfg.read_every:
        # step._phase_c read registration: START of phase C, pre-append
        # commit as the read point, gated like read_begin.
        gate = ((ns.commit == ns.last_index)
                | (_term_at(cfg, ns, ns.commit) == ns.term))
        reg = (lead & ((t % cfg.read_every) == 0)
               & (ns.sched_read_index < 0) & gate)
        ns = ns._replace(
            sched_read_index=jnp.where(reg, ns.commit, ns.sched_read_index),
            sched_read_reg=jnp.where(reg, t, ns.sched_read_reg),
        )

    if cfg.reconfig_u32:
        # step._phase_c scheduled reconfig: first tick of a firing epoch,
        # single-server toggle of a hash-chosen node, gated on the
        # previous config being committed + min-voters + current-term.
        epoch = t // cfg.reconfig_epoch
        fires = ((t % cfg.reconfig_epoch) == 0) & jrng.reconfig_fires(
            cfg.seed, g, epoch, cfg.reconfig_u32)
        target = jrng.reconfig_target(cfg.seed, g, epoch, cfg.k)
        voters, cfg_index = _current_config(cfg, ns)
        new_mask = voters ^ _onehot_mask(target, cfg.k)
        gate = ((_popcount(new_mask) >= cfg.effective_min_voters)
                & (cfg_index <= ns.commit)
                & (_term_at(cfg, ns, ns.commit) == ns.term))
        idx = ns.last_index + 1
        room = (idx - ns.snap_index) <= cfg.log_cap
        if df is not None:
            room = room & ~df
        do = lead & fires & gate & room
        sl = _slot(cfg, idx)
        ns = ns._replace(
            log_term=_lset(ns.log_term, sl, do, ns.term),
            log_payload=_lset(ns.log_payload, sl, do,
                              jnp.int32(CONFIG_FLAG) | new_mask),
            last_index=jnp.where(do, idx, ns.last_index),
        )

    last_index = ns.last_index
    log_term, log_payload = ns.log_term, ns.log_payload
    stopped = lead & (g < 0)                    # all-false, constant-free
    if cfg.clients_u32:
        # step._phase_c client block: every self-believed leader
        # appends the pulsed session ops in slot order, stopping at
        # window-full (dual-leader duplicates are the dedup fold's
        # job).
        for sl in range(cfg.client_slots):
            idx = last_index + 1
            room = (idx - ns.snap_index) <= cfg.log_cap
            if df is not None:
                room = room & ~df
            want = lead & (csub[sl] != 0)
            do = want & room & ~stopped
            s = _slot(cfg, idx)
            log_term = _lset(log_term, s, do, ns.term)
            log_payload = _lset(log_payload, s, do, cpay[sl])
            last_index = jnp.where(do, idx, last_index)
            stopped = stopped | (want & ~room)
    for _ in range(cfg.cmds_per_tick):
        idx = last_index + 1
        room = (idx - ns.snap_index) <= cfg.log_cap
        if df is not None:
            room = room & ~df
        do = lead & room & ~stopped
        payload = jrng.client_payload(cfg.seed, g, ns.term, idx)
        s = _slot(cfg, idx)
        log_term = _lset(log_term, s, do, ns.term)
        log_payload = _lset(log_payload, s, do, payload)
        last_index = jnp.where(do, idx, last_index)
        stopped = stopped | (lead & ~room)
    return ns._replace(last_index=last_index, log_term=log_term,
                       log_payload=log_payload)


def _commit_candidate_voters(cfg, match_index, last_index, i, voters):
    """ops.quorum.commit_candidate_voters as a compare-exchange network
    with a dynamic (one-hot-selected) pick: the voter_majority-th
    largest replication index among voters; -1 when no voters exist
    (the caller's n > commit guard rejects it)."""
    rows = []
    for j in range(cfg.k):
        v = jnp.where(jnp.int32(j) == i, last_index, match_index[j])
        rows.append(jnp.where(((voters >> j) & 1) == 1, v, jnp.int32(-1)))
    for a in range(cfg.k):          # selection-sort network, descending
        for b in range(a + 1, cfg.k):
            hi = jnp.maximum(rows[a], rows[b])
            lo = jnp.minimum(rows[a], rows[b])
            rows[a], rows[b] = hi, lo
    pick = _voter_majority(voters) - 1
    out = rows[0] & 0
    for j in range(cfg.k):
        out = out + jnp.where(pick == j, rows[j], 0)
    return out


def _phase_a(cfg, ns, g, i, t):
    if cfg.reconfig_u32 == 0:
        n = _commit_candidate(cfg, ns.match_index, ns.last_index, i)
    else:
        voters, cfg_index = _current_config(cfg, ns)
        n = _commit_candidate_voters(cfg, ns.match_index, ns.last_index,
                                     i, voters)
    advance = ((ns.role == LEADER) & (n > ns.commit)
               & (_term_at(cfg, ns, n) == ns.term))
    commit = jnp.where(advance, n, ns.commit)

    if cfg.reconfig_u32:
        # A removed leader steps down once its removal is committed
        # (step.py:738-748): latest config entry committed, self not in.
        self_voter = _bit_at(voters, i, cfg.k) == 1
        demote = (ns.role == LEADER) & (cfg_index <= commit) & ~self_voter
        ns = ns._replace(
            role=jnp.where(demote, FOLLOWER, ns.role),
            leader_id=jnp.where(demote, NO_VOTE, ns.leader_id),
            votes=ns.votes & ~demote,
        )
        ns = _drop_reads(cfg, ns, demote)

    # Apply loop with the exactly-once filter (step._phase_a): a
    # session command folds — and advances the [S] dedup table — iff
    # its seq strictly advances the sid's entry (sids pre-registered
    # 0..S-1; out-of-range sid == unknown session == no-op).
    applied, digest = ns.applied, ns.digest
    table = ns.session_seq
    for _ in range(cfg.log_cap):
        idx = applied + 1
        act = idx <= commit
        p = _payload_at(cfg, ns, idx)
        if cfg.clients_u32:
            is_sess = ((p & SESSION_FLAG) != 0) & ((p & CONFIG_FLAG) == 0)
            sid = (p >> SESSION_SID_SHIFT) & SESSION_SID_MASK
            seq = (p >> SESSION_SEQ_SHIFT) & SESSION_SEQ_MASK
            # _lget's in-range contract holds only under sid < S; an
            # out-of-range sid reads garbage that the eff_sess gate
            # discards, and _lset's one-hot cannot write it anywhere.
            cur = _lget(table, sid)
            eff_sess = is_sess & (sid < cfg.client_slots) & (seq > cur)
            table = _lset(table, sid, act & eff_sess, seq)
            fold = act & (~is_sess | eff_sess)
        else:
            fold = act
        digest = jnp.where(fold, jrng.digest_update(digest, idx, p), digest)
        applied = jnp.where(act, idx, applied)

    compact = (commit - ns.snap_index) >= cfg.compact_every
    if cfg.nem_compact:
        # Compaction pressure (r20, DESIGN.md §19): step._phase_a's
        # delayed-snapshot gate, hash compares only (Mosaic-legal).
        compact = compact & ~jrng.nem_compact_block(
            cfg.seed, cfg.nem_compact, g, i, t)
    sess = {}
    if cfg.clients_u32:
        sess = dict(session_seq=table,
                    snap_session_seq=jnp.where(compact, table,
                                               ns.snap_session_seq))
    ns = ns._replace(
        commit=commit, applied=applied, digest=digest, **sess,
        snap_term=jnp.where(compact, _term_at(cfg, ns, commit), ns.snap_term),
        snap_voters=jnp.where(compact, _committed_voters(cfg, ns, commit),
                              ns.snap_voters),
        snap_index=jnp.where(compact, commit, ns.snap_index),
        snap_digest=jnp.where(compact, digest, ns.snap_digest),
    )
    if cfg.read_every:
        # Scheduled-read completion (step.py phase A end): voters-aware
        # ReadIndex quorum over the ack evidence.
        sched = ns.sched_read_index >= 0
        recent = ns.ack_time >= ns.sched_read_reg + 2
        not_self = _col(cfg.k) != i
        if cfg.reconfig_u32 == 0:
            acks = jnp.sum((recent & not_self).astype(I32), axis=0)
            done = (sched & (acks + 1 >= cfg.majority)
                    & (ns.applied >= ns.sched_read_index))
        else:
            voters2, _ = _current_config(cfg, ns)
            acks = None
            for j in range(cfg.k):
                vlane = ((voters2 >> j) & 1) == 1
                term = (recent[j] & vlane & (jnp.int32(j) != i)).astype(I32)
                acks = term if acks is None else acks + term
            self_voter2 = _bit_at(voters2, i, cfg.k)
            done = (sched
                    & (acks + self_voter2 >= _voter_majority(voters2))
                    & (ns.applied >= ns.sched_read_index))
        ns = ns._replace(
            reads_done=ns.reads_done + done.astype(I32),
            sched_read_index=jnp.where(done, -1, ns.sched_read_index),
        )
    return ns


def _node_tick(cfg, t, ns: PerNode, inbox, g, i, glog_t, glog_p,
               csub=None, cpay=None):
    """step._node_tick, [8,128]-tile flavor; vmapped over the node axis.
    `csub`/`cpay` are the [S, 8, 128] client submit pulses + payloads,
    broadcast across nodes (None with clients off). The empty outbox
    derives its all-false rows from runtime data (module docstring)."""
    fK = jnp.broadcast_to(g, (cfg.k,) + g.shape) < 0
    zK = jnp.zeros((cfg.k, 1, 1), I32) + (g & 0)
    zKu = zK.astype(jnp.uint32)
    pv = {}
    if cfg.prevote:
        pv = dict(pv_req_present=fK, pv_req_term=zK, pv_req_lli=zK,
                  pv_req_llt=zK, pv_resp_present=fK, pv_resp_term=zK,
                  pv_resp_req_term=zK, pv_resp_granted=fK)
    if cfg.transfer_u32:
        pv.update(tn_present=fK, tn_term=zK)
    if cfg.clients_u32:
        pv["is_req_snap_sessions"] = \
            jnp.zeros((cfg.k, cfg.client_slots, 1, 1), I32) + (g & 0)
    out = Mailbox(
        rv_req_present=fK, rv_resp_present=fK, rv_resp_granted=fK,
        ae_req_present=fK, ae_resp_present=fK, ae_resp_success=fK,
        is_req_present=fK, is_resp_present=fK,
        rv_req_term=zK, rv_req_lli=zK, rv_req_llt=zK, rv_resp_term=zK,
        ae_req_term=zK, ae_req_prev_index=zK, ae_req_prev_term=zK,
        ae_req_n=zK, ae_req_commit=zK, ae_resp_term=zK, ae_resp_match=zK,
        is_req_term=zK, is_req_snap_index=zK, is_req_snap_term=zK,
        is_req_snap_digest=zKu, is_req_snap_voters=zK,
        is_resp_term=zK, is_resp_match=zK, **pv)
    # Hoisted own-ring geometry for the AE handlers: snap_index cannot
    # change before _on_is_req (ordered after every _on_ae_req call in
    # _HANDLERS) or phase A's compaction, so the [L, 8, 128] absolute-
    # index map and its live-window mask are computed once per tick and
    # shared across all K-1 senders instead of rebuilt per message.
    absidx = _abs_index(cfg, ns)
    gl = (glog_t, glog_p, t, absidx, absidx > ns.snap_index)
    for handler in _HANDLERS:
        for src in range(cfg.k):
            ns, out = handler(cfg, ns, out, g, i, src, inbox, gl)
    ns, out = _phase_t(cfg, ns, out, g, i, t)
    ns = _phase_c(cfg, ns, g, i, t, csub, cpay)
    ns = _phase_a(cfg, ns, g, i, t)
    # Outbox bools leave the per-node step widened to i32: the vmap
    # out_axes=1 stacking transposes the node axis, and Mosaic's i1
    # relayout path materializes mask constants LLO cannot build.
    out = jax.tree.map(
        lambda a: a.astype(I32) if a.dtype == jnp.bool_ else a, out)
    return ns, out


# ------------------------------------------------------------- global tick


def _apply_restart(cfg, nodes: PerNode, g, edge, t):
    """step._apply_restart on [K, 8, 128] leaves (edge: [K, 8, 128])."""
    kio = jax.lax.broadcasted_iota(I32, (cfg.k, 1, 1), 0)
    new_deadline = jrng.election_deadline(cfg.seed, g[None], kio,
                                          nodes.rng_draws, cfg.election_min,
                                          cfg.election_range)
    if cfg.nem_skew:
        new_deadline = jnp.maximum(1, new_deadline + jrng.nem_deadline_extra(
            cfg.seed, cfg.nem_skew, g[None], kio, t))
    e1 = edge[:, None]
    return nodes._replace(
        role=jnp.where(edge, FOLLOWER, nodes.role),
        leader_id=jnp.where(edge, NO_VOTE, nodes.leader_id),
        commit=jnp.where(edge, nodes.snap_index, nodes.commit),
        applied=jnp.where(edge, nodes.snap_index, nodes.applied),
        digest=jnp.where(edge, nodes.snap_digest, nodes.digest),
        votes=nodes.votes & ~e1,
        next_index=jnp.where(e1, 1, nodes.next_index),
        match_index=jnp.where(e1, 0, nodes.match_index),
        heartbeat_elapsed=jnp.where(edge, 0, nodes.heartbeat_elapsed),
        election_elapsed=jnp.where(edge, 0, nodes.election_elapsed),
        leader_elapsed=jnp.where(edge, 0, nodes.leader_elapsed),
        deadline=jnp.where(edge, new_deadline, nodes.deadline),
        rng_draws=nodes.rng_draws + edge.astype(I32),
        ack_time=jnp.where(e1, -1, nodes.ack_time),
        sched_read_index=jnp.where(edge, -1, nodes.sched_read_index),
        reads_done=jnp.where(edge, 0, nodes.reads_done),
        # Live dedup table rewinds to the snapshot table, like digest
        # (step._apply_restart).
        **({"session_seq": jnp.where(e1, nodes.snap_session_seq,
                                     nodes.session_seq)}
           if cfg.clients_u32 else {}),
    )


def _filter_mailbox(cfg, mb: Mailbox, t, alive_now, g) -> Mailbox:
    """step._filter_mailbox on [dst, src, 8, 128] leaves."""
    dst = jax.lax.broadcasted_iota(I32, (cfg.k, cfg.k, 1, 1), 0)
    src = jax.lax.broadcasted_iota(I32, (cfg.k, cfg.k, 1, 1), 1)
    gg = g[None, None]
    keep = alive_now[:, None]     # [K,1,8,128] dst-alive, broadcast over src
    if cfg.partition_u32:
        keep = keep & ~jrng.link_partitioned(cfg.seed, gg, t, src, dst,
                                             cfg.partition_u32,
                                             cfg.partition_epoch)
    if cfg.drop_u32:
        keep = keep & ~jrng.link_dropped(cfg.seed, gg, t, src, dst,
                                         cfg.drop_u32)
    if cfg.nem_link:
        # Nemesis link clauses (DESIGN.md §14; step._filter_mailbox).
        keep = keep & jrng.nem_link_ok(cfg.seed, cfg.nem_link, gg, t,
                                       src, dst, cfg.k)
    pv = {}
    if cfg.prevote:
        pv = dict(pv_req_present=mb.pv_req_present & keep,
                  pv_resp_present=mb.pv_resp_present & keep)
    if cfg.transfer_u32:
        pv["tn_present"] = mb.tn_present & keep
    return mb._replace(
        rv_req_present=mb.rv_req_present & keep,
        rv_resp_present=mb.rv_resp_present & keep,
        ae_req_present=mb.ae_req_present & keep,
        ae_resp_present=mb.ae_resp_present & keep,
        is_req_present=mb.is_req_present & keep,
        is_resp_present=mb.is_resp_present & keep,
        **pv,
    )


def _tick(cfg, nodes, mailbox, alive_prev, clients, g, t):
    """step.tick over k-state values. g: [8,128] group ids; t: scalar;
    `clients` the [S, 8, 128]-leaf ClientState (None when off)."""
    kio = jax.lax.broadcasted_iota(I32, (cfg.k, 1, 1), 0)
    if cfg.crash_u32 == 0:
        alive_now = jnp.broadcast_to(g[None], (cfg.k,) + g.shape) >= 0
    else:
        alive_now = jnp.broadcast_to(
            jrng.node_alive(cfg.seed, g[None], kio, t,
                            cfg.crash_u32, cfg.crash_epoch),
            (cfg.k,) + g.shape)
    if cfg.nem_crash:
        # Nemesis crash-storm clauses AND into the base crash schedule
        # (DESIGN.md §14; step.tick applies the same mask).
        alive_now = alive_now & jnp.broadcast_to(
            jrng.nem_alive(cfg.seed, cfg.nem_crash, g[None], kio, t),
            (cfg.k,) + g.shape)
    nodes = _apply_restart(cfg, nodes, g, alive_now & ~alive_prev, t)
    inbox = _filter_mailbox(cfg, mailbox, t, alive_now, g)

    csub = cpay = None
    if cfg.clients_u32:
        # Start-of-tick submit pulses + payloads (step.tick's client
        # block, [S, 8, 128] tiles): the SAME elementwise
        # clients/workload.py code as the XLA path, on kernel layouts.
        sio = jax.lax.broadcasted_iota(I32, (cfg.client_slots, 1, 1), 0)
        csub, cpay = _workload.submit_payloads(cfg, clients, g[None], sio)

    node_fn = functools.partial(_node_tick, cfg, t)
    new_nodes, outbox = jax.vmap(
        node_fn, in_axes=(0, 0, None, 0, None, None, None, None),
        out_axes=(0, 1))(
        nodes, inbox, g, kio, nodes.log_term, nodes.log_payload,
        csub, cpay)

    def freeze(new, old):
        m = alive_now.reshape(
            alive_now.shape[:1] + (1,) * (new.ndim - 3) + alive_now.shape[1:])
        if new.dtype == jnp.bool_:      # no select on i1 (Mosaic trunci)
            return (new & m) | (old & ~m)
        return jnp.where(m, new, old)

    new_nodes = jax.tree.map(freeze, new_nodes, nodes)
    src_alive = alive_now[None]        # [1, K_src, 8, 128]

    def erase(p):   # presence slots are i32 here (see _node_tick tail)
        return jnp.where(src_alive, p, 0)

    pv = {}
    if cfg.prevote:
        pv = dict(pv_req_present=erase(outbox.pv_req_present),
                  pv_resp_present=erase(outbox.pv_resp_present))
    if cfg.transfer_u32:
        pv["tn_present"] = erase(outbox.tn_present)
    outbox = outbox._replace(
        rv_req_present=erase(outbox.rv_req_present),
        rv_resp_present=erase(outbox.rv_resp_present),
        ae_req_present=erase(outbox.ae_req_present),
        ae_resp_present=erase(outbox.ae_resp_present),
        is_req_present=erase(outbox.is_req_present),
        is_resp_present=erase(outbox.is_resp_present),
        **pv,
    )
    if cfg.clients_u32:
        # Post-tick client transition on the frozen state (step.tick's
        # tail): table witness over the K axis, same elementwise update.
        tmax = _workload.table_max(new_nodes.session_seq, node_axis=0)
        sio = jax.lax.broadcasted_iota(I32, (cfg.client_slots, 1, 1), 0)
        clients = _workload.client_update(cfg, clients, tmax, g[None],
                                          sio, t)
    return new_nodes, outbox, alive_now, clients


# -------------------------------------------------------- kernel + wrapper

_MB_BOOL = ("rv_req_present", "rv_resp_present", "rv_resp_granted",
            "ae_req_present", "ae_resp_present", "ae_resp_success",
            "is_req_present", "is_resp_present",
            "pv_req_present", "pv_resp_present", "pv_resp_granted",
            "tn_present")

_PV_MB = ("pv_req_present", "pv_req_term", "pv_req_lli", "pv_req_llt",
          "pv_resp_present", "pv_resp_term", "pv_resp_req_term",
          "pv_resp_granted")
_TN_MB = ("tn_present", "tn_term")


class KMetrics(NamedTuple):
    """Per-group metric tiles carried through the kernel ([8, 128] per
    block; [GS, 128] in HBM). Field order IS the wire order
    (METRIC_LEAVES; scripts/check_metric_parity.py pins the two).
    `elections` / `max_latency` are per-GROUP here (run.Metrics keeps
    scalars) and `hist` is a per-group [H, 8, 128] streak-length
    histogram ([H, GS, 128] in HBM) — each group's lane accumulates its
    own bucket counts, updated by a one-hot row add (Mosaic has no
    scatter), and kfinish reduces over groups host-side. Integer adds
    reassociate exactly, so the reduced histogram is bit-identical to
    the XLA path's global scatter-add. `safety` is the per-group
    per-tick safety AND (run.Metrics.safety) — a pass-through lane:
    kinit loads the caller's bits, the kernel ANDs into them, kfinish
    reads them back. The client lanes (DESIGN.md §10; None with
    clients off, like run.Metrics): `client_acked`/`client_retries`
    are idempotent per-tick recomputes from the client state,
    `client_max_lat` accumulates per group like max_latency, and
    `client_hist` is a second [H, 8, 128] row set for ack latencies."""
    committed: jnp.ndarray = None
    leaderless: jnp.ndarray = None
    elections: jnp.ndarray = None
    max_latency: jnp.ndarray = None
    safety: jnp.ndarray = None
    hist: jnp.ndarray = None
    client_acked: jnp.ndarray = None
    client_retries: jnp.ndarray = None
    client_max_lat: jnp.ndarray = None
    client_hist: jnp.ndarray = None


def _safety_tick(cfg, nodes, cl=None):
    """check.tick_safety on k-state tiles, one [8, 128] bit per group:
    election safety (pairwise leader term compare), digest agreement on
    equal applied prefixes, per-node window bounds, leader completeness
    (r18: each leader's log covers every node's committed prefix —
    commit_b <= last_index_a plus payload agreement on the committed
    ring overlap, over ordered pairs with term_a >= term_b), and
    (clients on) the exactly-once invariant (check.client_safety:
    pairwise dedup-table agreement + no table seq above the issued
    frontier) — term-for-term the predicates in verify/invariants.py
    via sim/check.py, statically unrolled over K (and K^2 pairs) like
    every other kernel reduction."""
    ok = None
    for j in range(cfg.k):
        wb = ((nodes.applied[j] == nodes.commit[j])
              & (nodes.snap_index[j] <= nodes.commit[j])
              & (nodes.commit[j] <= nodes.last_index[j])
              & (nodes.last_index[j] - nodes.snap_index[j] <= cfg.log_cap))
        ok = wb if ok is None else ok & wb
    # Per-node ring slot -> absolute index ([L, 8, 128] each), hoisted
    # out of the pair loops: invariants.slot_abs_index == _abs_index.
    absidx = []
    for j in range(cfg.k):
        off = _col(cfg.log_cap) - nodes.snap_index[j] % cfg.log_cap
        absidx.append(nodes.snap_index[j] + 1
                      + jnp.where(off >= 0, off, off + cfg.log_cap))
    for a in range(cfg.k):
        for b in range(a + 1, cfg.k):
            clash = ((nodes.role[a] == LEADER) & (nodes.role[b] == LEADER)
                     & (nodes.term[a] == nodes.term[b]))
            split = ((nodes.applied[a] == nodes.applied[b])
                     & (nodes.digest[a] != nodes.digest[b]))
            ok = ok & ~clash & ~split
    for a in range(cfg.k):
        for b in range(cfg.k):
            if a == b:
                continue
            cond = ((nodes.role[a] == LEADER)
                    & (nodes.term[a] >= nodes.term[b]))
            lim = jnp.minimum(nodes.commit[b], nodes.last_index[a])
            m = (absidx[a] == absidx[b]) & (absidx[a] <= lim)
            bad = ((nodes.commit[b] > nodes.last_index[a])
                   | jnp.any(m & (nodes.log_payload[a]
                                  != nodes.log_payload[b]), axis=0))
            ok = ok & ~(cond & bad)
    if cl is not None:
        table = nodes.session_seq                     # [K, S, 8, 128]
        for j in range(cfg.k):
            for s in range(cfg.client_slots):
                ok = ok & (table[j, s] <= cl.done[s])
        for a in range(cfg.k):
            for b in range(a + 1, cfg.k):
                diff = None
                for s in range(cfg.client_slots):
                    d = table[a, s] != table[b, s]
                    diff = d if diff is None else diff | d
                ok = ok & ~((nodes.applied[a] == nodes.applied[b]) & diff)
    return ok


def _presence_fields(cfg):
    """The mailbox occupancy fields present under `cfg`, in the shared
    obs.recorder.PRESENCE_FIELDS order (None-skipping on the XLA side,
    static gating here — same surviving list)."""
    skip = set()
    if not cfg.prevote:
        skip.update(("pv_req_present", "pv_resp_present"))
    if not cfg.transfer_u32:
        skip.add("tn_present")
    return [f for f in PRESENCE_FIELDS if f not in skip]


def _metrics_tick(cfg, m: KMetrics, fl, nodes, mailbox, alive_now, t,
                  cl=None):
    """run.metrics_update + obs.recorder.flight_update against k-state
    values — histograms, safety bit, client SLO lanes (`cl` is the
    POST-transition client state, None with clients off), and (when
    `fl` is not None) the flight-recorder ring. `mailbox` is the
    post-tick outbox (presence already widened to i32); `t` the
    absolute tick."""
    committed = jnp.maximum(m.committed, jnp.max(nodes.commit, axis=0))
    has_leader = jnp.any((nodes.role == LEADER) & alive_now, axis=0)
    done = has_leader & (m.leaderless > 0)
    safe = _safety_tick(cfg, nodes, cl)
    hist = m.hist
    if hist is not None:   # wire_hist dial off => no rows to maintain
        hsize = hist.shape[0]
        bucket = jnp.minimum(m.leaderless, hsize - 1)
        hrow = jax.lax.broadcasted_iota(I32, (hsize, 1, 1), 0)
        hist = hist + ((hrow == bucket) & done).astype(I32)
    clm = {}
    if cl is not None:
        # Client SLO lanes (run.metrics_update's client fold): acked /
        # retry totals recomputed from the client state (idempotent),
        # this tick's completion events one-hot-added into the
        # per-group ack-latency rows (a `last_lat` of -1 — no event —
        # matches no row; rows absent under the wire_hist dial), and
        # the per-group running max.
        acked = retries = None
        for s in range(cfg.client_slots):
            acked = cl.done[s] if acked is None else acked + cl.done[s]
            retries = cl.retries[s] if retries is None \
                else retries + cl.retries[s]
        chist = m.client_hist
        if chist is not None:
            csize = chist.shape[0]
            crow = jax.lax.broadcasted_iota(I32, (csize, 1, 1), 0)
        cmax = m.client_max_lat
        for s in range(cfg.client_slots):
            ev = cl.last_lat[s] >= 0
            if chist is not None:
                chist = chist + ((crow == jnp.minimum(cl.last_lat[s],
                                                      csize - 1))
                                 & ev).astype(I32)
            cmax = jnp.maximum(cmax, jnp.where(ev, cl.last_lat[s], 0))
        clm = dict(client_acked=acked, client_retries=retries,
                   client_hist=chist, client_max_lat=cmax)
    met = KMetrics(
        committed=committed,
        leaderless=jnp.where(has_leader, 0, m.leaderless + 1),
        elections=m.elections + done.astype(I32),
        max_latency=jnp.maximum(m.max_latency,
                                jnp.where(done, m.leaderless, 0)),
        safety=jnp.where(safe, m.safety, 0),
        hist=hist,
        **clm,
    )
    if fl is None:
        return met, None
    # Flight ring: overwrite row t % RING of each per-group ring with
    # this tick's aggregates (obs/recorder.py flight_update, k-state
    # flavor; the one-hot row select is the histogram's pattern).
    on = _col(fl.tick.shape[0]) == (t % fl.tick.shape[0])
    leaders = None
    for j in range(cfg.k):
        v = ((nodes.role[j] == LEADER) & alive_now[j]).astype(I32)
        leaders = v if leaders is None else leaders + v
    commit_max = nodes.commit[0]
    for j in range(1, cfg.k):
        commit_max = jnp.maximum(commit_max, nodes.commit[j])
    msgs = None
    for f in _presence_fields(cfg):
        p = getattr(mailbox, f)   # i32 [K, K, 8, 128] post-tick
        v = jnp.sum(jnp.sum(p, axis=0), axis=0)
        msgs = v if msgs is None else msgs + v

    def w(r, val):
        return jnp.where(on, val, r)

    fl = Flight(tick=w(fl.tick, t), leaders=w(fl.leaders, leaders),
                elections=w(fl.elections, done.astype(I32)),
                commit=w(fl.commit, commit_max), msgs=w(fl.msgs, msgs),
                safety=w(fl.safety, safe.astype(I32)))
    return met, fl


_SESS_NODE_FIELDS = ("session_seq", "snap_session_seq")


def _node_leaves(cfg):
    """(field, kind) per PerNode leaf present under `cfg`;
    kind: 'scalar'|'peer'|'ring'|'sess'. The session tables exist only
    with scheduled clients on (None fields — sim/state.py)."""
    kinds = {"votes": "peer", "next_index": "peer", "match_index": "peer",
             "ack_time": "peer", "log_term": "ring", "log_payload": "ring",
             "session_seq": "sess", "snap_session_seq": "sess"}
    return [(f, kinds.get(f, "scalar")) for f in PerNode._fields
            if cfg.clients_u32 or f not in _SESS_NODE_FIELDS]


def _mb_fields(cfg):
    """Static names of the mailbox leaves present under `cfg` (PreVote /
    TimeoutNow slots exist only when their schedules are on, mirroring
    state.empty_mailbox). NO array construction: this runs inside the
    kernel trace, where even a dead jnp.zeros(bool) lowers to an i1
    vector constant LLO rejects."""
    skip = set()
    if not cfg.prevote:
        skip.update(_PV_MB)
    if not cfg.transfer_u32:
        skip.update(_TN_MB)
    if not cfg.clients_u32:
        skip.add("is_req_snap_sessions")
    return [f for f in Mailbox._fields if f not in skip]


def _fold_g(a):
    """[..., G] -> [..., G/LANE, LANE]."""
    return a.reshape(a.shape[:-1] + (a.shape[-1] // LANE, LANE))


def _unfold_g(a):
    return a.reshape(a.shape[:-2] + (a.shape[-2] * a.shape[-1],))


def _widen_klane(a):
    """bool / narrow-native lanes (DESIGN.md §18) -> the i32 wire width.
    u32 digest lanes pass through — the wire dtype map is exactly r18's
    regardless of the narrow dials (every byte pin unchanged)."""
    if a.dtype != I32 and a.dtype != jnp.uint32:
        return a.astype(I32)
    return a


def _to_kstate(cfg, st: State):
    """State (G a GB multiple) -> flat list of k-state arrays (leaf
    order: node leaves, mailbox leaves, client-state leaves (clients
    on), alive_prev, group_id; bools AND narrow-native lanes widened to
    i32 — a narrow resident State enters the kernel through the same
    unchanged wire; trailing G folded to [GS, LANE]). Every leaf moves
    its leading G axis last — the one transpose rule all ranks share
    ([G, K] -> [K, G], [G, K, X] -> [K, X, G],
    [G, d, s, S] -> [d, s, S, G])."""
    out = []
    for f, _ in _node_leaves(cfg):
        a = jnp.moveaxis(getattr(st.nodes, f), 0, -1)
        out.append(_fold_g(_widen_klane(a)))
    for f in _mb_fields(cfg):
        a = jnp.moveaxis(getattr(st.mailbox, f), 0, -1)
        out.append(_fold_g(_widen_klane(a)))
    if cfg.clients_u32:
        for f in active_client_leaves(cfg):
            out.append(_fold_g(_widen_klane(
                jnp.moveaxis(getattr(st.clients, f), 0, -1))))
    out.append(_fold_g(jnp.transpose(st.alive_prev, (1, 0)).astype(I32)))
    out.append(_fold_g(st.group_id))
    return out


def _from_kstate(cfg, flat, g: int) -> State:
    """Inverse of _to_kstate from UNFOLDED (flat-G) leaves, slicing off
    any pad groups beyond `g`."""
    it = iter(a[..., :g] for a in flat)
    nd = {}
    for f, _ in _node_leaves(cfg):
        nd[f] = jnp.moveaxis(next(it), -1, 0)
    nd["votes"] = nd["votes"].astype(BOOL)
    nd["snap_digest"] = nd["snap_digest"].astype(jnp.uint32)
    nd["digest"] = nd["digest"].astype(jnp.uint32)
    md = {}
    for f in _mb_fields(cfg):
        a = jnp.moveaxis(next(it), -1, 0)
        if f in _MB_BOOL:
            a = a.astype(BOOL)
        elif f == "is_req_snap_digest":
            a = a.astype(jnp.uint32)
        md[f] = a
    clients = None
    if cfg.clients_u32:
        clients = ClientState(**{f: jnp.moveaxis(next(it), -1, 0)
                                 for f in active_client_leaves(cfg)})
    alive = jnp.transpose(next(it), (1, 0)).astype(BOOL)
    gid = next(it)
    return State(nodes=PerNode(**nd), mailbox=Mailbox(**md),
                 alive_prev=alive, group_id=gid, clients=clients)


# -------------------------------------------------- packed wire layout
# The pack_bools / pack_ring dials (DESIGN.md §13). Packing happens
# ONLY at chunk boundaries — host-side in kinit/kfinish and at the
# kernel's load/store edges — so every per-tick value inside the
# fori_loop is the identical unpacked form and tick semantics cannot
# drift with the layout. Both functions run on host ([..., GS, LANE])
# and in-kernel ([..., 8, 128]) shapes alike: they only touch leading
# axes with static indices, shifts and masks (Mosaic-safe; no i1
# constants, no concatenate — stacking is one-hot sums, the histogram
# row pattern).


def _mb_bool_fields(cfg):
    """Bool mailbox leaves present under `cfg`, in Mailbox field
    order — the shared-lane set of the pack_bools dial (bit index =
    field-position x k + src)."""
    return [f for f in _mb_fields(cfg) if f in _MB_BOOL]


def _unpacked_names(cfg):
    """Wire-leaf names of the UNPACKED state section, in r12 registry
    order — the list `_to_kstate` emits and the kernel body consumes."""
    return ([f for f, _ in _node_leaves(cfg)] + list(_mb_fields(cfg))
            + (list(active_client_leaves(cfg)) if cfg.clients_u32 else [])
            + ["alive_prev", "group_id"])


def _stack0(rows):
    """Stack equal-shape arrays along a NEW leading axis via one-hot
    sums (no concatenate — Mosaic lowering)."""
    io = jax.lax.broadcasted_iota(I32, (len(rows),) + (1,) * rows[0].ndim,
                                  0)
    acc = None
    for j, r in enumerate(rows):
        t = jnp.where(io == j, r[None], 0)
        acc = t if acc is None else acc + t
    return acc


def _stack1(rows):
    """Stack [K, ...] arrays along a NEW axis 1 -> [K, n, ...]."""
    io = jax.lax.broadcasted_iota(
        I32, (1, len(rows)) + (1,) * (rows[0].ndim - 1), 1)
    acc = None
    for j, r in enumerate(rows):
        t = jnp.where(io == j, r[:, None], 0)
        acc = t if acc is None else acc + t
    return acc


def _ring_base_ov(cfg, log_term):
    """(base, overflow) of the ring-delta encoding: per-group min term
    over the [K, L] window, and 1 where any delta exceeds the 16-bit
    half-lane (the encode would wrap — kfinish refuses on the flag,
    never returns silently wrong terms)."""
    base = jnp.min(jnp.min(log_term, axis=0), axis=0)
    spread = jnp.max(jnp.max(log_term, axis=0), axis=0) - base
    return base, (spread > 0xFFFF).astype(I32)


def _pack_wire(cfg, flat, aux=None):
    """Unpacked wire list (bools widened to i32, `_unpacked_names`
    order) -> packed wire list (`_wire_state_leaves` order). Identity
    when every packing dial is off. `aux` is the dict the matching
    `_unpack_wire` returned — it carries the sticky ring-overflow bit
    so a chunk that decoded an already-overflowed wire re-encodes the
    flag (None = fresh encode, i.e. kinit)."""
    if not (cfg.pack_bools or cfg.pack_ring):
        return list(flat)
    d = dict(zip(_unpacked_names(cfg), flat))
    mbb = _mb_bool_fields(cfg) if cfg.pack_bools else []
    ring = _ring_base_ov(cfg, d["log_term"]) if cfg.pack_ring else None
    out = []
    for name, _ in _wire_state_leaves(cfg):
        if cfg.pack_bools and name == "votes":
            v = d["votes"]
            acc = v[:, 0] & 1
            for p in range(1, cfg.k):
                acc = acc | ((v[:, p] & 1) << p)
            out.append(acc)
        elif name == MB_BOOLS_PACKED:
            n_words = -(-len(mbb) * cfg.k // 32)
            words = [None] * n_words
            for fi, f in enumerate(mbb):
                leaf = d[f]
                for s in range(cfg.k):
                    b = fi * cfg.k + s
                    t = (leaf[:, s] & 1) << (b % 32)
                    words[b // 32] = t if words[b // 32] is None \
                        else words[b // 32] | t
            out.append(_stack1(words))
        elif cfg.pack_bools and name == "alive_prev":
            a = d["alive_prev"]
            acc = a[0] & 1
            for j in range(1, cfg.k):
                acc = acc | ((a[j] & 1) << j)
            out.append(acc)
        elif cfg.pack_ring and name == "log_term":
            base = ring[0]
            delta = d["log_term"] - base[None, None]
            out.append(_stack1(
                [(delta[:, 2 * j] & 0xFFFF)
                 | ((delta[:, 2 * j + 1] & 0xFFFF) << 16)
                 for j in range(cfg.log_cap // 2)]))
        elif name == RING_BASE:
            base, ov = ring
            if aux is not None and "ring_ov" in aux:
                ov = ov | aux["ring_ov"]
            out.append(base | (ov << 31))
        else:
            out.append(d[name])
    return out


def _unpack_wire(cfg, flat):
    """Packed wire list -> (unpacked list in `_unpacked_names` order,
    aux). Exact inverse of `_pack_wire` for every in-range encoding;
    `aux["ring_ov"]` carries the sticky overflow bit back to the next
    pack (kfinish checks it host-side and raises)."""
    if not (cfg.pack_bools or cfg.pack_ring):
        return list(flat), {}
    d = dict(zip([n for n, _ in _wire_state_leaves(cfg)], flat))
    out, aux = {}, {}
    if cfg.pack_bools:
        pv = d["votes"]
        out["votes"] = _stack1([(pv >> q) & 1 for q in range(cfg.k)])
        pm = d[MB_BOOLS_PACKED]
        for fi, f in enumerate(_mb_bool_fields(cfg)):
            rows = []
            for s in range(cfg.k):
                b = fi * cfg.k + s
                rows.append((pm[:, b // 32] >> (b % 32)) & 1)
            out[f] = _stack1(rows)
        pa = d["alive_prev"]
        out["alive_prev"] = _stack0([(pa >> j) & 1 for j in range(cfg.k)])
    if cfg.pack_ring:
        bl = d[RING_BASE]
        aux["ring_ov"] = (bl >> 31) & 1
        base = bl & 0x7FFFFFFF
        pk = d["log_term"]
        out["log_term"] = _stack1(
            [base[None] + ((pk[:, sl // 2] >> (16 * (sl % 2))) & 0xFFFF)
             for sl in range(cfg.log_cap)])
    return [out[n] if n in out else d[n] for n in _unpacked_names(cfg)], aux


def _check_ring_overflow(cfg, leaves, g: int):
    """Host-side refusal on the sticky delta-overflow flag: a >=2^16
    in-group term spread cannot be 16-bit delta-encoded, and silently
    wrong terms must never leave kfinish. Re-run with pack_ring off
    (the universe itself is fine — only the wire encoding saturated)."""
    if not cfg.pack_ring:
        return
    import numpy as np
    base = np.asarray(_unfold_g(leaves[_wire_index(cfg, RING_BASE)]))[:g]
    if (base < 0).any():   # bit 31 = the sticky overflow flag
        raise ValueError(
            f"pack_ring: ring-term delta overflowed the 16-bit half-lane "
            f"in {int((base < 0).sum())} group(s) (in-group term spread "
            f">= 2^16) — state cannot be decoded; re-run with "
            f"pack_ring=False")


def _build_kernel(cfg, n_ticks, with_flight):
    """The pallas kernel body: load block -> fori_loop of ticks -> store.
    `with_flight` (static) adds the six flight-recorder ring leaves
    between the group ids and the metric tail (wire order)."""
    node_kinds = _node_leaves(cfg)
    mb_fields = _mb_fields(cfg)
    n_state = _n_state_leaves(cfg)
    n_in = (n_state
            + (len(FLIGHT_LEAVES) if with_flight else 0)
            + _n_metric_leaves(cfg))

    def kernel(t0_ref, *refs):
        in_refs = refs[:n_in]
        out_refs = refs[n_in:]
        # Chunk-boundary DECODE (DESIGN.md §13): the packed wire leaves
        # expand to the r12 unpacked form once per launch; everything
        # below — the fori_loop included — sees identical values
        # whatever the layout dials say. `aux` carries the sticky
        # ring-overflow bit through to the re-encode.
        state_flat, aux = _unpack_wire(cfg, [r[:] for r in
                                             in_refs[:n_state]])
        it = iter(state_flat)
        nd = {}
        for f, kind in node_kinds:
            a = next(it)
            if f == "votes":
                a = a != 0
            elif f in ("snap_digest", "digest"):
                a = a.astype(jnp.uint32)
            nd[f] = a
        md = {}
        for f in mb_fields:
            a = next(it)
            if f in _MB_BOOL:
                a = a != 0
            elif f == "is_req_snap_digest":
                a = a.astype(jnp.uint32)
            md[f] = a
        cl = None
        if cfg.clients_u32:
            cl = ClientState(**{f: next(it)
                                for f in active_client_leaves(cfg)})
        alive_prev = next(it) != 0
        g = next(it)
        tail = iter(in_refs[n_state:])
        fl = None
        if with_flight:
            fl = Flight(**{f: next(tail)[:] for f in FLIGHT_LEAVES})
        met = KMetrics(**{f: next(tail)[:]
                          for f in _active_metric_leaves(cfg)})
        nodes = PerNode(**nd)
        mailbox = Mailbox(**md)
        t0 = t0_ref[0]

        # The loop carry is i32-only: Mosaic fails to legalize scf.for
        # with i1 vector block arguments, so bool leaves cross the loop
        # boundary widened and are re-derived each iteration. (KMetrics
        # and Flight leaves are i32 by construction — safety included.)
        def widen(tree):
            return jax.tree.map(
                lambda a: a.astype(I32) if a.dtype == jnp.bool_ else a, tree)

        def narrow_like(tree, proto):
            return jax.tree.map(
                lambda a, pr: a != 0 if pr.dtype == jnp.bool_ else a,
                tree, proto)

        proto = (nodes, mailbox, alive_prev, cl)

        def body(tt, carry):
            state_i, met, fl = carry
            nodes, mailbox, alive_prev, cl = narrow_like(state_i, proto)
            nodes, mailbox, alive_now, cl = _tick(cfg, nodes, mailbox,
                                                  alive_prev, cl, g,
                                                  t0 + tt)
            met, fl = _metrics_tick(cfg, met, fl, nodes, mailbox,
                                    alive_now, t0 + tt, cl)
            return widen((nodes, mailbox, alive_now, cl)), met, fl

        state_i, met, fl = jax.lax.fori_loop(
            0, n_ticks, body,
            (widen((nodes, mailbox, alive_prev, cl)), met, fl))
        nodes, mailbox, alive_prev, cl = narrow_like(state_i, proto)

        # Chunk-boundary ENCODE: widen to the i32 unpacked list, pack
        # per the layout dials, write the wire.
        outs = []
        for f, _ in node_kinds:
            a = getattr(nodes, f)
            outs.append(a.astype(I32)
                        if a.dtype in (jnp.bool_, jnp.uint32) else a)
        for f in mb_fields:
            a = getattr(mailbox, f)
            outs.append(a.astype(I32)
                        if a.dtype in (jnp.bool_, jnp.uint32) else a)
        if cfg.clients_u32:
            outs.extend(getattr(cl, f) for f in active_client_leaves(cfg))
        outs.append(alive_prev.astype(I32))
        outs.append(g)
        ot = iter(out_refs)
        for a in _pack_wire(cfg, outs, aux):
            next(ot)[:] = a
        if with_flight:
            for f in FLIGHT_LEAVES:
                next(ot)[:] = getattr(fl, f)
        for f in _active_metric_leaves(cfg):
            next(ot)[:] = getattr(met, f)

    return kernel


def _gspec(a):
    """BlockSpec cutting SUB-wide slices of the folded GS axis (dim -2)."""
    lead = a.shape[:-2]
    zeros = (0,) * len(lead)

    def imap(b, _z=zeros):
        return _z + (b, 0)

    return pl.BlockSpec(lead + (SUB, LANE), imap)


def _prun_padded_impl(cfg, leaves, t0, n_ticks, interpret=False):
    with_flight = len(leaves) > _n_state_leaves(cfg) + _n_metric_leaves(cfg)
    kernel = _build_kernel(cfg, n_ticks, with_flight)
    nb = leaves[0].shape[-2] // SUB
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    in_specs += [_gspec(a) for a in leaves]
    out_shape = [jax.ShapeDtypeStruct(a.shape, I32) for a in leaves]
    out_specs = [_gspec(a) for a in leaves]
    t0a = jnp.asarray([t0], I32)
    # Input/output aliasing (DESIGN.md §13): every wire input donates
    # its HBM buffer to the same-shaped output (operand i+1 -> result
    # i; operand 0 is the SMEM t0). Safe because the grid visits each
    # block exactly once and fully overwrites it. Compiled path only —
    # the interpret path runs as plain XLA where aliasing buys nothing
    # and some jaxlib versions reject the kwarg-in-interpreter combo.
    ioa = {}
    if cfg.alias_wire and not interpret:
        ioa = {i + 1: i for i in range(len(leaves))}
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_shape=out_shape,
        out_specs=out_specs,
        input_output_aliases=ioa,
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES),
    )(t0a, *leaves)


_prun_padded = jax.jit(_prun_padded_impl,
                       static_argnames=("cfg", "n_ticks", "interpret"))
# The donating twin `kstep` dispatches to under cfg.alias_wire: the
# wire operands' buffers are released to the launch, so ONE wire copy
# is resident instead of in+out — the other half of the §13 aliasing
# lever (pallas aliases the custom call; jit donation lets XLA actually
# reuse the operand buffers). Callers must treat passed-in leaves as
# consumed, which every chunk loop in the repo already does
# (`leaves = kstep(leaves, ...)`).
_prun_padded_donate = jax.jit(_prun_padded_impl,
                              static_argnames=("cfg", "n_ticks",
                                               "interpret"),
                              donate_argnums=(1,))


def kinit(cfg: RaftConfig, st: State, metrics: Metrics | None = None,
          flight: Flight | None = None, pad_to: int = GB):
    """Convert (State, Metrics[, Flight]) to the kernel wire form ONCE.
    Returns (leaves, g): `leaves` is the flat tuple `kstep` launches on,
    `g` the unpadded group count. Passing a `flight`
    (obs.recorder.flight_init) turns on the in-kernel flight-recorder
    ring — its six leaves ride the wire between the group ids and the
    metric tail, and `kflight` reads them back. `pad_to` rounds the
    padded group count up to a multiple of its value (itself a multiple
    of the GB block size): the sharded driver (parallel/kmesh.py) passes
    n_devices * GB so every device shard holds whole blocks. The
    conversion transposes the whole state; at 100K groups it costs more
    than a 200-tick kernel launch, so chunked drivers must call
    kinit/kfinish once around the chunk loop, never per chunk (that
    mistake hid the kernel's speed behind 2s/chunk of host-side
    reshuffling when first measured)."""
    from raft_tpu.sim.run import metrics_init
    if pad_to % GB:
        raise ValueError(f"pad_to={pad_to} must be a multiple of the "
                         f"{GB}-group block")
    g = st.alive_prev.shape[0]
    if metrics is None:
        metrics = metrics_init(g, clients=cfg.clients_u32 != 0)
    pad = (-g) % pad_to
    if pad:
        # Pad groups simulate alongside (results sliced off at finish);
        # their group ids continue past g, keeping seed streams distinct.
        def padg(a):
            w = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, w)
        stp = jax.tree.map(padg, st)
        stp = stp._replace(group_id=jnp.concatenate(
            [st.group_id, jnp.arange(g, g + pad, dtype=I32)]))
    else:
        stp = st
    leaves = _to_kstate(cfg, stp)
    fleaves = []
    if flight is not None:
        for name in FLIGHT_LEAVES:
            a = getattr(flight, name)
            if pad:
                a = jnp.pad(a, ((0, 0), (0, pad)),
                            constant_values=-1 if name == "tick" else 0)
            fleaves.append(_fold_g(a))
    # elections / max_latency / hist / client_max_lat / client_hist
    # accumulate from zero in-kernel and kfinish folds the caller's
    # metrics_base back in (scalars add/max, histograms add
    # bucket-wise); committed / leaderless / safety / client_acked /
    # client_retries are pass-through lanes the kernel continues in
    # place. Nothing of `metrics` is lost either way. Order:
    # _active_metric_leaves(cfg).
    def lane(a, fill=0):
        a = jnp.zeros(g, I32) if a is None else a
        return _fold_g(jnp.pad(a, (0, pad), constant_values=fill)
                       if pad else a)

    def rows():
        return _fold_g(jnp.zeros((metrics.hist.shape[0], g + pad), I32))

    mvals = {"committed": lane(metrics.committed),
             "leaderless": lane(metrics.leaderless),
             "elections": lane(None), "max_latency": lane(None),
             "safety": lane(metrics.safety, fill=1)}
    if cfg.wire_hist:
        # The §13 telemetry dial: with wire_hist off the [H]-row leaves
        # never ride the wire (and the kernel tracks no histograms).
        mvals["hist"] = rows()
    if cfg.clients_u32:
        mvals.update(client_acked=lane(metrics.client_acked),
                     client_retries=lane(metrics.client_retries),
                     client_max_lat=lane(None))
        if cfg.wire_hist:
            mvals["client_hist"] = rows()
    mleaves = [mvals[n] for n in _active_metric_leaves(cfg)]
    return tuple(_pack_wire(cfg, leaves) + fleaves + mleaves), g


def kstep(cfg: RaftConfig, leaves, t0: int, n_ticks: int,
          interpret: bool = False):
    """One kernel launch: `n_ticks` ticks starting at absolute tick
    `t0` (traced — chunked calls at advancing t0 reuse one compile).
    Returns the evolved leaves tuple. Under `cfg.alias_wire` (compiled
    path) the input leaves' buffers are DONATED to the launch — stale
    after the call, exactly like the chunk loops already use them."""
    fn = _prun_padded_donate if (cfg.alias_wire and not interpret) \
        else _prun_padded
    return tuple(fn(cfg, tuple(leaves), int(t0), int(n_ticks),
                    interpret=interpret))


METRIC_LEAVES = ("committed", "leaderless", "elections", "max_latency",
                 "safety", "hist", "client_acked", "client_retries",
                 "client_max_lat", "client_hist")
# Wire order of the metric tail == KMetrics._fields (parity-checked by
# scripts/check_metric_parity.py). The client leaves ride the wire only
# when cfg.clients_u32 (`_active_metric_leaves`); they come AFTER the
# protocol leaves so a clients-off wire is byte-identical to pre-r09.
CLIENT_METRIC_LEAVES = ("client_acked", "client_retries",
                        "client_max_lat", "client_hist")
ROW_METRIC_LEAVES = ("hist", "client_hist")   # [H]-row (not lane) leaves
N_METRIC_LEAVES = len(METRIC_LEAVES)


def _active_metric_leaves(cfg) -> tuple:
    """The metric leaves actually on the wire under `cfg`, in
    METRIC_LEAVES order: client lanes ride only with clients on, the
    [H]-row histogram leaves only under the `wire_hist` telemetry dial
    (DESIGN.md §13 — with it off the kernel tracks no latency
    histograms and kfinish passes the caller's rows through)."""
    names = METRIC_LEAVES if cfg.clients_u32 else tuple(
        n for n in METRIC_LEAVES if n not in CLIENT_METRIC_LEAVES)
    if not cfg.wire_hist:
        names = tuple(n for n in names if n not in ROW_METRIC_LEAVES)
    return names


def _n_metric_leaves(cfg) -> int:
    return len(_active_metric_leaves(cfg))


def _n_row_metrics(cfg) -> int:
    """[H]-row metric leaves on the wire (1, or 2 with the client
    ack-latency histogram)."""
    return sum(1 for n in _active_metric_leaves(cfg)
               if n in ROW_METRIC_LEAVES)


def _n_state_leaves(cfg) -> int:
    """Wire leaves ahead of the (flight, metrics) tail — the packed-
    layout registry's length (node + mailbox leaves packed per the cfg
    dials + the client-state leaves with clients on + alive_prev +
    group_id)."""
    return len(_wire_state_leaves(cfg))


def _mleaf(cfg, leaves, name: str):
    """The named metric leaf of a wire tuple — indexed by active-leaf
    position from the END (the metric tail is last whether or not
    flight leaves ride the wire), so adding a leaf cannot silently
    shift the counters the bench reads (kcommitted/kelections/khist)."""
    active = _active_metric_leaves(cfg)
    return leaves[active.index(name) - len(active)]


def kcommitted(cfg, leaves, g: int) -> int:
    """Host-side total committed rounds from the wire form (int64 sum —
    run.total_rounds semantics)."""
    import numpy as np
    mc = np.asarray(_unfold_g(_mleaf(cfg, leaves, "committed")))[:g]
    return int(mc.astype(np.int64).sum())


def kreads(cfg, leaves, g: int) -> int:
    """Host-side total completed scheduled reads (sum of the per-node
    `reads_done` counters), straight from the wire form — indexed by
    NAME through the packed-layout registry (the packing dials insert/
    remove wire leaves, so positional constants would silently read a
    neighbor)."""
    import numpy as np
    rd = np.asarray(_unfold_g(
        leaves[_wire_index(cfg, "reads_done")]))[..., :g]   # [K, g]
    return int(rd.astype(np.int64).sum())


def kelections(cfg, leaves, g: int) -> int:
    import numpy as np
    me = np.asarray(_unfold_g(_mleaf(cfg, leaves, "elections")))[:g]
    return int(me.astype(np.int64).sum())


def kacked(cfg, leaves, g: int) -> int:
    """Host-side client-visible committed ops (run.total_client_ops
    semantics), straight from the wire form — the client segments'
    timed counter."""
    import numpy as np
    ma = np.asarray(_unfold_g(_mleaf(cfg, leaves, "client_acked")))[:g]
    return int(ma.astype(np.int64).sum())


def kretries(cfg, leaves, g: int) -> int:
    import numpy as np
    mr = np.asarray(_unfold_g(_mleaf(cfg, leaves, "client_retries")))[:g]
    return int(mr.astype(np.int64).sum())


def khist(cfg, leaves, g: int, name: str = "hist"):
    """Host-side [H] histogram from the wire form: the per-group [H, G]
    accumulators of the real groups, reduced to the run.Metrics [H]
    layout (i32 sum, matching the kernel's and the XLA scatter-add's
    dtype — exact in any order). `name` picks the election-latency
    (default) or client ack-latency rows. kfinish folds this into its
    returned Metrics."""
    import numpy as np
    mh = np.asarray(_unfold_g(_mleaf(cfg, leaves, name)))[:, :g]
    return mh.sum(axis=1, dtype=np.int32)


def kflight(cfg: RaftConfig, leaves, g: int) -> Flight | None:
    """Host-side Flight from the wire form ([RING, g] per leaf, pad
    groups sliced off), or None when kinit ran without a flight."""
    n_state = _n_state_leaves(cfg)
    n_flight = len(leaves) - n_state - _n_metric_leaves(cfg)
    if n_flight == 0:
        return None
    if n_flight != len(FLIGHT_LEAVES):
        # ValueError, not assert (stripped under python -O): a wrong
        # count means mis-assigned leaves, which must fail loudly, not
        # feed garbage into the flight_identical gate.
        raise ValueError(
            f"wire tuple has {n_flight} leaves between the state and "
            f"metric tails; expected 0 or {len(FLIGHT_LEAVES)} (a Flight)")
    return Flight(*[jnp.asarray(_unfold_g(a))[:, :g]
                    for a in leaves[n_state:n_state + n_flight]])


def kfinish(cfg: RaftConfig, leaves, g: int,
            metrics_base: Metrics | None = None):
    """Wire form -> (State, Metrics). `metrics_base` supplies prior
    elections/max_latency scalars and histogram counts to fold in —
    continuation semantics identical to passing `metrics` to run.run
    (committed / leaderless / safety were continued in place on the
    wire, like the state itself). The histogram is REAL: per-group
    in-kernel accumulators reduced over groups (bit-identical to the
    XLA scatter-add). Flight leaves, when present, are skipped here —
    read them with `kflight`."""
    from raft_tpu.sim.run import metrics_init
    clients_on = cfg.clients_u32 != 0
    if metrics_base is None:
        metrics_base = metrics_init(g, clients=clients_on)
    n_state = _n_state_leaves(cfg)
    # Refuse on the sticky ring-overflow flag BEFORE decoding: a
    # saturated delta encode cannot be inverted.
    _check_ring_overflow(cfg, leaves, g)
    flat, _ = _unpack_wire(cfg, list(leaves[:n_state]))
    st = _from_kstate(cfg, [_unfold_g(a) for a in flat], g)
    from raft_tpu.sim import state as state_mod
    if state_mod.narrow_active(cfg):
        # Narrow resident layout (DESIGN.md §18): the wire gid lane
        # carried any pre-existing latch through the chunk untouched
        # (the tick never writes group_id); re-narrowing here re-checks
        # every narrowed leaf and the host boundary refuses a latched
        # state loudly — the same refusal _check_ring_overflow gives
        # the packed-ring wire dial.
        st = state_mod.narrow_state(cfg, st)
        state_mod.check_narrow_overflow(cfg, st)
    mc, ml, me, mx, ms = [
        _unfold_g(_mleaf(cfg, leaves, n))[:g]
        for n in ("committed", "leaderless", "elections", "max_latency",
                  "safety")]
    # Under the wire_hist dial the kernel tracked no histogram rows:
    # the caller's base rows pass through unchanged (telemetry simply
    # stops accumulating — the dial's documented contract).
    hist = metrics_base.hist
    if cfg.wire_hist:
        hist = hist + khist(cfg, leaves, g)
    cl = {}
    if clients_on:
        # Pass-through lanes read back; the accumulate-from-zero rows /
        # maxes fold the base in, mirroring the protocol leaves (a base
        # without client lanes contributes zeros).
        ca, cr, cm = [_unfold_g(_mleaf(cfg, leaves, n))[:g]
                      for n in ("client_acked", "client_retries",
                                "client_max_lat")]
        base_h = (metrics_base.client_hist
                  if metrics_base.client_hist is not None
                  else jnp.zeros((), I32))
        base_m = (metrics_base.client_max_lat
                  if metrics_base.client_max_lat is not None
                  else jnp.zeros((), I32))
        chist = base_h
        if cfg.wire_hist:
            chist = chist + khist(cfg, leaves, g, name="client_hist")
        cl = dict(client_acked=ca, client_retries=cr,
                  client_hist=chist,
                  client_max_lat=jnp.maximum(jnp.asarray(base_m, I32),
                                             jnp.max(cm)))
    met = Metrics(
        committed=mc, leaderless=ml,
        elections=metrics_base.elections + jnp.sum(me),
        hist=hist,
        max_latency=jnp.maximum(metrics_base.max_latency, jnp.max(mx)),
        safety=ms,
        **cl,
    )
    return st, met


def prun(cfg: RaftConfig, st: State, n_ticks: int, t0: int = 0,
         metrics: Metrics | None = None, interpret: bool = False,
         flight: Flight | None = None):
    """Drop-in for `sim.run.run` on supported configs: same (State,
    Metrics) out, same bits — latency histogram and safety bit
    included. Passing `flight` mirrors `obs.recorder.run_recorded`:
    the in-kernel ring rides along and a (State, Metrics, Flight)
    triple comes back. One launch + both conversions — for chunked
    loops use kinit/kstep/kfinish directly. Raises ValueError on
    unsupported shapes (supported(), single-device HBM budget included
    — the group count is in hand here)."""
    g = st.alive_prev.shape[0]
    wf = flight is not None
    if not supported(cfg, n_groups=g, with_flight=wf):
        raise ValueError(
            "pkernel: shape unsupported (k > 30, VMEM footprint "
            f"{kernel_vmem_bytes(cfg)} B > {VMEM_LIMIT_BYTES} B, or "
            f"single-device HBM {hbm_bytes(cfg, g, with_flight=wf)} B > "
            f"{HBM_LIMIT_BYTES} B) — use the XLA path (run.run)")
    leaves, g = kinit(cfg, st, metrics, flight)
    # Chunk-boundary span (obs.trace; no-op without a tracer): chunked
    # prun drivers — dryrun, triage re-execution — leave one span per
    # launch on the kernel lane of a --trace-dir timeline.
    from raft_tpu.obs import trace as _trace
    with _trace.chunk_span("pallas", int(t0), int(n_ticks),
                           interpret=bool(interpret)):
        leaves = kstep(cfg, leaves, t0, n_ticks, interpret=interpret)
    if flight is None:
        return kfinish(cfg, leaves, g, metrics)
    st2, met = kfinish(cfg, leaves, g, metrics)
    return st2, met, kflight(cfg, leaves, g)
