"""Scanned multi-tick runner + metrics for the batched path (DESIGN.md §6).

`run` wraps `sim.step.tick` in `lax.scan` under `jit`, so a whole
N-tick simulation is one device program: state stays resident in HBM,
zero host<->device traffic inside the loop.

Metrics:
- `committed[G]`: running max over ticks of the per-group max commit
  index — total entries durably committed by the group ("consensus
  rounds"; a restart rewinds a node's local commit knowledge, never the
  group's achievement, hence the running max).
- election latency (leaderless-interval, DESIGN.md §6): per group, the
  length of each leaderless streak — consecutive ticks with no alive
  leader — recorded when a leader (re)appears. Streaks land in a bounded
  histogram `[0..H)`; bucket H-1 absorbs anything longer, and
  `max_latency` tracks the exact longest completed streak so censoring
  is always detectable: `latency_censored(hist, q)` says whether the
  q-quantile hit the absorbing bucket. p50/p99 are computed host-side
  from the histogram (`latency_quantile`).

- `safety[G]` (DESIGN.md §8): a per-group running AND of the per-tick
  safety predicate `check.tick_safety` (election safety, digest
  agreement, window bounds). 1 = every tick of the run satisfied every
  invariant; 0 = at least one tick violated at least one — so a
  violation that exists for a single tick between check boundaries
  (two leaders in the same term that never coexist at an endpoint)
  still latches. Folded in-kernel on the Pallas path for the same
  reason the histogram is: a host readback would dominate the tick,
  a handful of vreg compares does not.

Both engines fold the same metrics every tick: this scanned path
scatter-adds into the global histogram directly; the Pallas fused-chunk
kernel (sim/pkernel.py) accumulates per-group histogram lanes in-kernel
and reduces them over groups at kfinish — bit-identical, since i32 adds
reassociate exactly (held by tests/test_pkernel.py and bench.py's
in-run fault-segment differentials). The per-tick flight-recorder ring
rides the same fold via `raft_tpu.obs.recorder.run_recorded`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import RaftConfig
from raft_tpu.core.node import LEADER
from raft_tpu.sim import check
from raft_tpu.sim.state import I32, State, widen_state
from raft_tpu.sim.step import tick

HIST_SIZE = 512


class Metrics(NamedTuple):
    committed: jnp.ndarray   # i32[G] — running max of per-group max commit
    leaderless: jnp.ndarray  # i32[G] — current leaderless streak, in ticks
    elections: jnp.ndarray   # i32 — completed leader-acquisition events
    hist: jnp.ndarray        # i32[H] — election-latency histogram
    max_latency: jnp.ndarray  # i32 — exact longest completed streak
    safety: jnp.ndarray      # i32[G] — per-tick safety AND (1 = never bad)
    # Client-visible SLO lanes (DESIGN.md §10) — present only when the
    # scheduled client traffic is on (None = empty subtree, keeping
    # clients-off metric pytrees identical to pre-r09). The safety
    # lane above then also latches the exactly-once invariant
    # (check.client_safety) every tick.
    client_acked: jnp.ndarray | None = None    # i32[G] — ops acked
    client_retries: jnp.ndarray | None = None  # i32[G] — re-submissions
    client_hist: jnp.ndarray | None = None     # i32[H] — ack-latency hist
    client_max_lat: jnp.ndarray | None = None  # i32 — longest acked op


def metrics_init(n_groups: int, hist_size: int = HIST_SIZE,
                 clients: bool = False) -> Metrics:
    """Zero metrics; pass `clients=True` for a scheduled-client
    universe (the lanes are folded iff `State.clients` is present, so
    a mismatched flag fails loudly in metrics_update, not silently)."""
    cl = {}
    if clients:
        cl = dict(client_acked=jnp.zeros(n_groups, I32),
                  client_retries=jnp.zeros(n_groups, I32),
                  client_hist=jnp.zeros(hist_size, I32),
                  client_max_lat=jnp.zeros((), I32))
    return Metrics(
        committed=jnp.zeros(n_groups, I32),
        leaderless=jnp.zeros(n_groups, I32),
        elections=jnp.zeros((), I32),
        hist=jnp.zeros(hist_size, I32),
        max_latency=jnp.zeros((), I32),
        safety=jnp.ones(n_groups, I32),
        **cl,
    )


def metrics_update(m: Metrics, st: State, log_cap: int) -> Metrics:
    """Fold one post-tick state into the metrics. `log_cap` bounds the
    window check inside the per-tick safety fold (check.tick_safety)."""
    nodes = st.nodes
    committed = jnp.maximum(m.committed, jnp.max(nodes.commit, axis=1))
    has_leader = jnp.any((nodes.role == LEADER) & st.alive_prev, axis=1)
    done = has_leader & (m.leaderless > 0)
    hist_size = m.hist.shape[0]
    bucket = jnp.minimum(m.leaderless, hist_size - 1)
    cl = {}
    if st.clients is not None:
        if m.client_acked is None:
            raise ValueError(
                "state carries client traffic but the metrics have no "
                "client lanes — init with metrics_init(g, clients=True)")
        c = st.clients
        # Acked/retry totals are monotone client-state counters —
        # recomputed per tick (idempotent), not accumulated, so chunk
        # boundaries cannot double-count. The ack-latency histogram
        # folds this tick's completion events (`last_lat` >= 0, one
        # per slot at most), exactly like the election histogram folds
        # completed leaderless streaks.
        ev = c.last_lat >= 0
        cb = jnp.where(ev, jnp.minimum(c.last_lat, hist_size - 1), 0)
        cl = dict(
            client_acked=jnp.sum(c.done, axis=1),
            client_retries=jnp.sum(c.retries, axis=1),
            client_hist=m.client_hist.at[cb.ravel()].add(
                ev.ravel().astype(I32)),
            client_max_lat=jnp.maximum(
                m.client_max_lat, jnp.max(jnp.where(ev, c.last_lat, 0))),
        )
    return m._replace(
        committed=committed,
        leaderless=jnp.where(has_leader, 0, m.leaderless + 1),
        elections=m.elections + jnp.sum(done.astype(I32)),
        hist=m.hist.at[bucket].add(done.astype(I32)),
        max_latency=jnp.maximum(
            m.max_latency, jnp.max(jnp.where(done, m.leaderless, 0))),
        safety=jnp.where(check.tick_safety(st, log_cap), m.safety, 0),
        **cl,
    )


def _run_impl(cfg: RaftConfig, st: State, n_ticks: int, t0=0,
              metrics: Metrics | None = None):
    if metrics is None:
        metrics = metrics_init(st.alive_prev.shape[0],
                               clients=st.clients is not None)

    def body(carry, t):
        s, m = carry
        s = tick(cfg, s, t)
        # Metrics/safety fold on the WIDE view of the post-tick state —
        # the predicates and histogram arithmetic stay at the audited
        # i32 widths regardless of the narrow dials (a few fused
        # elementwise casts; the scan carry itself stays narrow, which
        # is where the resident-byte win lives — DESIGN.md §18).
        return (s, metrics_update(m, widen_state(cfg, s),
                                  cfg.log_cap)), None

    (st, metrics), _ = jax.lax.scan(
        body, (st, metrics), t0 + jnp.arange(n_ticks, dtype=I32))
    return st, metrics


_run = jax.jit(_run_impl, static_argnums=(0, 2))
# Donating twin (cfg.donate_scan, DESIGN.md §18): the (state, metrics)
# carry buffers are released to the scan program, so XLA writes the
# updated carry in place — one resident copy instead of in+out, the
# scan-path analogue of the kernel's alias_wire donation
# (pkernel.kstep / kmesh._kstep_sharded_donate). Same consumed-operand
# contract: the caller's arrays are stale after the call, the way
# every chunked driver already treats them.
_run_donated = jax.jit(_run_impl, static_argnums=(0, 2),
                       donate_argnums=(1, 4))


def run(cfg: RaftConfig, st: State, n_ticks: int, t0=0,
        metrics: Metrics | None = None):
    """Run `n_ticks` global ticks starting at absolute tick `t0`.

    Returns (state, metrics). Donatable; call again with the returned
    state and `t0 + n_ticks` to continue the same deterministic universe.
    Under `cfg.donate_scan` the input state/metrics buffers are donated
    to the program (stale after the call); donation is skipped when no
    metrics operand exists to donate, keeping the twin's signature
    contract exact.
    """
    if cfg.donate_scan and metrics is not None:
        return _run_donated(cfg, st, n_ticks, t0, metrics)
    return _run(cfg, st, n_ticks, t0, metrics)


TRACE_FIELDS = ("term", "role", "voted_for", "leader_id", "last_index",
                "commit", "applied", "digest", "snap_index", "snap_term",
                "snap_voters", "reads_done")


@functools.partial(jax.jit, static_argnums=(0, 2))
def trace(cfg: RaftConfig, st: State, n_ticks: int, t0=0):
    """Run `n_ticks` and return (state, trace) where trace is a dict of
    stacked per-tick observables `[T, G, K]` — the fields `Cluster.snapshot`
    exposes (cluster.py:141), for the differential gate. One device
    program; no per-tick host round-trips."""

    def body(s, t):
        s = tick(cfg, s, t)
        # Trace rows are observed WIDE so the differential surface's
        # dtypes match the oracle's regardless of the narrow dials.
        sw = widen_state(cfg, s)
        obs = {f: getattr(sw.nodes, f) for f in TRACE_FIELDS}
        obs["alive"] = sw.alive_prev
        return s, obs

    return jax.lax.scan(body, st, t0 + jnp.arange(n_ticks, dtype=I32))


def total_rounds(metrics: Metrics) -> int:
    """Total consensus rounds = entries durably committed across groups.

    Summed host-side in int64: at 10^5 groups x 10^4+ ticks the total
    exceeds int32, and x64 is off on-device."""
    return int(np.asarray(metrics.committed).astype(np.int64).sum())


def latency_quantile(hist, q: float) -> int:
    """q-quantile (in ticks) of the election-latency histogram, host-side."""
    h = np.asarray(hist)
    total = h.sum()
    if total == 0:
        return 0
    cum = np.cumsum(h)
    return int(np.searchsorted(cum, q * total, side="left"))


def unsafe_groups(metrics: Metrics) -> int:
    """Host-side count of groups whose per-tick safety bit dropped at
    any point in the run (0 = the whole run was a clean soak). Benches,
    the dryrun, and the kernel sweep print this next to every number."""
    return int((np.asarray(metrics.safety) == 0).sum())


def total_client_ops(metrics: Metrics) -> int:
    """Client-visible committed ops (acked exactly-once) across groups,
    host-side int64 — the client-SLO analogue of total_rounds."""
    return int(np.asarray(metrics.client_acked).astype(np.int64).sum())


def total_client_retries(metrics: Metrics) -> int:
    """Re-submissions across groups — every one a potential duplicate
    log entry the exactly-once fold must (and provably does) skip."""
    return int(np.asarray(metrics.client_retries).astype(np.int64).sum())


def latency_censored(hist, q: float) -> bool:
    """True iff the q-quantile landed in the absorbing top bucket — i.e.
    the reported quantile is a floor, not a measurement. Benches must
    surface this flag next to any quantile they print."""
    h = np.asarray(hist)
    return h.sum() > 0 and latency_quantile(hist, q) >= h.shape[0] - 1
