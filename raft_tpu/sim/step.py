"""The batched TPU tick: `core/node.py` + `core/transport.py` as pure array ops.

Every handler here mirrors a method of the CPU oracle `Node`
branch-for-branch (mask-for-branch); the differential suite
(`tests/test_differential.py`) holds the two bit-identical per node per
tick. Handlers are written for ONE node — scalar state fields, `[K]`
peer vectors, `[L]` log rings, an inbox with a `[K_src]` leading axis —
and lifted with `vmap` over the node axis then the group axis
(DESIGN.md §5). The sequential tick contract (DESIGN.md §2: canonical
(type, src) inbox order) becomes a statically unrolled chain of masked
handler applications: 6 message types x K senders, each application
fully vectorized over the [G, K] batch, which is where the parallelism
lives. No data-dependent control flow anywhere — everything is
`jnp.where`.

Faults (DESIGN.md §4) are applied at the batch level: the delivery
filter masks mailbox occupancy bits, crash masks freeze dead nodes'
state wholesale and erase their outbox, and the dead->alive edge applies
`Node.restart()` semantics (durable survives, volatile rewinds).

Observability note (DESIGN.md §8): `tick` itself carries NO telemetry —
it stays the minimal reference program both engines are pinned to. The
per-tick safety fold and flight-recorder capture read the POST-tick
state from outside: `run.metrics_update` / `obs.recorder.flight_update`
here, `pkernel._metrics_tick` in-kernel. Changing tick semantics
changes what those folds attest; keep check.tick_safety's invariants
true at every tick boundary.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from raft_tpu.config import (CONFIG_FLAG, SESSION_FLAG, SESSION_SEQ_MASK,
                             SESSION_SEQ_SHIFT, SESSION_SID_MASK,
                             SESSION_SID_SHIFT, RaftConfig)
from raft_tpu.core.node import (CANDIDATE, FOLLOWER, LEADER, NO_VOTE,
                                PRECANDIDATE)
from raft_tpu.ops import quorum
from raft_tpu.sim.state import (BOOL, I32, Mailbox, PerNode, State,
                                empty_mailbox)
from raft_tpu.utils import jrng

# --------------------------------------------------------------- log helpers
# Ring addressing: absolute index i lives in slot (i - 1) % L. See
# sim/state.py module docstring for why this is injective over the window.
#
# All dynamic reads/writes over the L axis are one-hot select+reduce
# arithmetic, NOT indexed gather/scatter: under the double vmap an
# `arr[idx]` / `arr.at[idx].set` with a per-lane index lowers to XLA
# gather/scatter HLOs, which TPU executes orders of magnitude slower
# than the equivalent fused compare+select+reduce over a 32-wide minor
# axis (measured ~1s/tick -> ~ms/tick at 50K groups).


def _slot(cfg: RaftConfig, idx):
    return (idx - 1) % cfg.log_cap


def _lget(arr, idx):
    """arr[idx] over the trailing (L or E) axis via one-hot reduce."""
    return jnp.sum(jnp.where(jnp.arange(arr.shape[-1]) == idx, arr, 0), -1)


def _lset(arr, idx, cond, val):
    """Masked arr[idx] = val over the trailing axis via one-hot select."""
    return jnp.where((jnp.arange(arr.shape[-1]) == idx) & cond, val, arr)


def _term_at(cfg, ns: PerNode, idx):
    """`Node.term_at` (node.py:65). Valid for snap_index <= idx <= last_index;
    masked garbage outside that range (callers guard)."""
    return jnp.where(idx == ns.snap_index, ns.snap_term,
                     _lget(ns.log_term, _slot(cfg, idx)))


def _payload_at(cfg, ns: PerNode, idx):
    return _lget(ns.log_payload, _slot(cfg, idx))


def _last_log_term(cfg, ns: PerNode):
    return _term_at(cfg, ns, ns.last_index)


def _put(arr, p: int, cond, val):
    """Masked write of outbox slot p (p is a static unroll index)."""
    return arr.at[p].set(jnp.where(cond, val, arr[p]))


# ------------------------------------------------------- membership config


def _abs_index(cfg, ns: PerNode):
    """i32[L]: the absolute index each live-window ring slot holds
    (>= snap_index + 1 by construction; slots beyond last_index are
    stale and must be masked by the caller). The modulo is taken on the
    per-node SCALAR and expanded with a compare+select: an [L]-wide
    integer remainder is a multi-op sequence on TPU that measurably
    dominated phase D when tried (DESIGN.md §7)."""
    off = jnp.arange(cfg.log_cap, dtype=I32) - ns.snap_index % cfg.log_cap
    return ns.snap_index + 1 + jnp.where(off >= 0, off, off + cfg.log_cap)


def _config_scan(cfg, ns: PerNode, through):
    """(voters, cfg_index): the config entry with the highest absolute
    index <= `through` in the live window, else the snapshot's config —
    `Node.current_config` / `Node.committed_config` (derived, never
    stored: truncation reverts membership with no bookkeeping)."""
    absidx = _abs_index(cfg, ns)
    is_cfg = (((ns.log_payload & CONFIG_FLAG) != 0)
              & (absidx <= jnp.minimum(ns.last_index, through)))
    best = jnp.max(jnp.where(is_cfg, absidx, 0), -1)   # 0 == none (abs >= 1)
    found = best > 0
    mask_at = jnp.sum(
        jnp.where(is_cfg & (absidx == best[..., None]), ns.log_payload, 0),
        -1) & cfg.full_mask
    return (jnp.where(found, mask_at, ns.snap_voters),
            jnp.where(found, best, ns.snap_index))


def _current_config(cfg, ns: PerNode):
    # Static fast path (round-4 VERDICT item 1): with the reconfig
    # schedule statically off, no CONFIG_FLAG payload can ever enter any
    # log — the only batched-path source is `_phase_c`'s scheduled
    # proposal, itself gated on `cfg.reconfig_u32`. The config is then a
    # compile-time constant, and returning it here lets XLA fold every
    # downstream voter computation (vote quorums, commit tallies,
    # self-voter gates, removed-leader demotion) out of the tick program
    # instead of paying ~7 O(L) ring scans per node per tick.
    if cfg.reconfig_u32 == 0:
        return jnp.int32(cfg.full_mask), ns.snap_index
    return _config_scan(cfg, ns, jnp.int32(0x7FFFFFFF))


def _committed_voters(cfg, ns: PerNode, commit):
    if cfg.reconfig_u32 == 0:
        return jnp.int32(cfg.full_mask)
    return _config_scan(cfg, ns, commit)[0]


def _vote_quorum(cfg, ns: PerNode, votes):
    """`Node._vote_quorum`: granted votes from CURRENT-config voters
    reach that config's majority. The single static-vs-dynamic branch
    point for every election path (RV tally, PV tally, instant win)."""
    if cfg.reconfig_u32 == 0:   # static full-config quorum (fast path)
        return quorum.vote_count(votes) >= cfg.majority
    voters, _ = _current_config(cfg, ns)
    return quorum.vote_won(votes, voters, cfg.k)


# -------------------------------------------------------------- transitions


def _reset_timer(cfg, ns: PerNode, g, i, cond, t):
    """`Node._reset_election_timer` (node.py:89): one counted draw.
    `t` is the absolute tick of the draw — consumed only by the
    statically-gated nemesis clock-skew clauses (DESIGN.md §14), so
    the skew-off program is unchanged."""
    deadline = jrng.election_deadline(cfg.seed, g, i, ns.rng_draws,
                                      cfg.election_min, cfg.election_range)
    if cfg.nem_skew:
        deadline = jnp.maximum(1, deadline + jrng.nem_deadline_extra(
            cfg.seed, cfg.nem_skew, g, i, t))
    return ns._replace(
        election_elapsed=jnp.where(cond, 0, ns.election_elapsed),
        deadline=jnp.where(cond, deadline, ns.deadline),
        rng_draws=ns.rng_draws + cond.astype(I32),
    )


def _drop_reads(cfg, ns: PerNode, cond):
    """`Node._drop_client_state` for the scheduled-read fields: pending
    read aborts, deference evidence is stale. Statically absent when the
    read schedule is off."""
    if not cfg.read_every:
        return ns
    return ns._replace(
        ack_time=jnp.where(cond, -1, ns.ack_time),
        sched_read_index=jnp.where(cond, -1, ns.sched_read_index),
    )


def _step_down(cfg, ns: PerNode, new_term, cond):
    """`Node._step_down` (node.py:96): adopt term, follower, no timer reset."""
    ns = ns._replace(
        term=jnp.where(cond, new_term, ns.term),
        role=jnp.where(cond, FOLLOWER, ns.role),
        voted_for=jnp.where(cond, NO_VOTE, ns.voted_for),
        leader_id=jnp.where(cond, NO_VOTE, ns.leader_id),
        votes=jnp.where(cond, False, ns.votes),
    )
    return _drop_reads(cfg, ns, cond)


def _become_leader(cfg, ns: PerNode, i, cond):
    """`Node._become_leader` (node.py:104) incl. the takeover re-proposal
    (DESIGN.md §2a): the TOP entry takes the new term in place."""
    ns = _drop_reads(cfg, ns, cond)
    ns = ns._replace(
        role=jnp.where(cond, LEADER, ns.role),
        leader_id=jnp.where(cond, i, ns.leader_id),
        next_index=jnp.where(cond, ns.last_index + 1, ns.next_index),
        match_index=jnp.where(cond, 0, ns.match_index),
        heartbeat_elapsed=jnp.where(cond, cfg.heartbeat_every,
                                    ns.heartbeat_elapsed),
    )
    top = cond & (ns.last_index > ns.commit)
    return ns._replace(
        log_term=_lset(ns.log_term, _slot(cfg, ns.last_index), top, ns.term))


def _accept_leader(cfg, ns: PerNode, g, i, src: int, cond, t):
    """`Node._accept_leader` (node.py:194)."""
    ns = ns._replace(
        role=jnp.where(cond, FOLLOWER, ns.role),
        leader_id=jnp.where(cond, src, ns.leader_id),
        votes=jnp.where(cond, False, ns.votes),
        leader_elapsed=jnp.where(cond, 0, ns.leader_elapsed),
    )
    return _reset_timer(cfg, ns, g, i, cond, t)


# ----------------------------------------------------------------- phase D


def _on_rv_req(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_rv_req` (node.py:169)."""
    present = ib.rv_req_present[src]
    m_term, m_lli, m_llt = (ib.rv_req_term[src], ib.rv_req_lli[src],
                            ib.rv_req_llt[src])
    ns = _step_down(cfg, ns, m_term, present & (m_term > ns.term))
    llt = _last_log_term(cfg, ns)
    log_ok = (m_llt > llt) | ((m_llt == llt) & (m_lli >= ns.last_index))
    grant = (present & (m_term == ns.term)
             & ((ns.voted_for == NO_VOTE) | (ns.voted_for == src))
             & log_ok)
    ns = ns._replace(voted_for=jnp.where(grant, src, ns.voted_for))
    ns = _reset_timer(cfg, ns, g, i, grant, gl[2])
    out = out._replace(
        rv_resp_present=_put(out.rv_resp_present, src, present, True),
        rv_resp_term=_put(out.rv_resp_term, src, present, ns.term),
        rv_resp_granted=_put(out.rv_resp_granted, src, present, grant),
    )
    return ns, out


def _on_rv_resp(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_rv_resp` (node.py:184)."""
    present = ib.rv_resp_present[src]
    m_term, m_granted = ib.rv_resp_term[src], ib.rv_resp_granted[src]
    higher = present & (m_term > ns.term)
    ns = _step_down(cfg, ns, m_term, higher)
    cont = (present & ~higher & (ns.role == CANDIDATE)
            & (m_term == ns.term) & m_granted)
    votes = ns.votes.at[src].set(ns.votes[src] | cont)
    ns = ns._replace(votes=votes)
    won = cont & _vote_quorum(cfg, ns, votes)
    return _become_leader(cfg, ns, i, won), out


def _on_ae_req(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_ae_req` (node.py:201): the log-matching workhorse.

    Entry payloads are PULLED from the sender's ring (`gl` — the whole
    group's end-of-previous-tick log arrays, [K, L]) rather than carried
    in the message; see the Mailbox docstring for the bit-exactness
    argument. `gl[0][src]` / `gl[1][src]` are the sender's term/payload
    rings with `src` static, so each entry read is one masked reduce of
    a group-broadcast array — far cheaper than the send-side gather
    loop this replaces."""
    glog_t, glog_p, _ = gl
    present = ib.ae_req_present[src]
    m_term = ib.ae_req_term[src]
    m_prev = ib.ae_req_prev_index[src]
    m_prev_term = ib.ae_req_prev_term[src]
    m_n = ib.ae_req_n[src]
    m_commit = ib.ae_req_commit[src]
    # The j-th sent entry has absolute index m_prev+1+j; its value lives
    # at the sender's ring slot for that index (valid under j < m_n).
    ent_t = [_lget(glog_t[src], _slot(cfg, m_prev + 1 + j))
             for j in range(cfg.max_entries_per_msg)]
    ent_p = [_lget(glog_p[src], _slot(cfg, m_prev + 1 + j))
             for j in range(cfg.max_entries_per_msg)]

    ns = _step_down(cfg, ns, m_term, present & (m_term > ns.term))
    stale = present & (m_term < ns.term)
    ok = present & ~stale
    ns = _accept_leader(cfg, ns, g, i, src, ok, gl[2])

    past = ok & (m_prev > ns.last_index)
    conflict = (ok & ~past & (m_prev >= ns.snap_index)
                & (_term_at(cfg, ns, m_prev) != m_prev_term))
    # Fast-backup to the first index of the conflicting term: the CPU
    # oracle walks back one index at a time (node.py:219-223); here the
    # walk collapses to one vectorized pass over the ring — ci is one
    # past the highest in-window index BELOW m_prev whose term differs
    # from ct (clamped to snap_index when the run reaches the snapshot).
    ct = _term_at(cfg, ns, m_prev)
    absidx = _abs_index(cfg, ns)   # scalar-mod form — see its docstring
    bad = ((absidx > ns.snap_index) & (absidx < m_prev)
           & (ns.log_term != ct))
    # min with m_prev covers the degenerate m_prev == snap_index case,
    # where the CPU walk never moves and returns m_prev itself.
    ci = jnp.minimum(jnp.max(jnp.where(bad, absidx, ns.snap_index)) + 1,
                     m_prev)

    proceed = ok & ~past & ~conflict
    # Entry walk (node.py:229-256), split decide-then-write: this handler
    # alone was ~51% of the whole tick (DESIGN.md §7), dominated by the
    # E chained read-modify-write ring passes below. Entries at idx <=
    # snap_index are committed here hence match (Log Matching) — skipped
    # via j0.
    j0 = jnp.maximum(0, ns.snap_index - m_prev)
    hi = m_prev + j0
    last_index = ns.last_index
    stopped = jnp.zeros((), BOOL)
    # Storage pressure (r20, DESIGN.md §19): a disk-full node's appends
    # all fail — non-durable entries are never acked, so `hi` (hence
    # the match reply and the commit clamp) stops at the durable
    # prefix and the leader's retransmission is the NACK loop. Matching
    # entries still advance `hi`, in-place term rewrites (same_p) stay
    # live, and a divergent suffix is still truncated — mirroring the
    # oracle, where only `_append` itself consults the budget.
    df = jnp.zeros((), BOOL)
    if cfg.nem_disk:
        df = jrng.nem_disk_full(cfg.seed, cfg.nem_disk, g, i,
                                gl[2], cfg.k)
    # Stage 1 — decide: per-entry scalar chain. Reads go to the ORIGINAL
    # log arrays: the E entries address E consecutive absolute indices,
    # whose ring slots are pairwise distinct (E <= L, config invariant),
    # so within one message no write feeds a later read.
    write_t, write_p, slots = [], [], []   # per-entry write masks + slots
    for j in range(cfg.max_entries_per_msg):
        idx = m_prev + 1 + j
        act = proceed & (j >= j0) & (j < m_n) & ~stopped
        s = _slot(cfg, idx)
        slots.append(s)
        in_log = act & (idx <= last_index)
        # act => idx > snap_index, so a direct slot read IS term_at(idx).
        same_t = in_log & (_lget(ns.log_term, s) == ent_t[j])
        same_p = in_log & ~same_t & (_lget(ns.log_payload, s) == ent_p[j])
        diverge = in_log & ~same_t & ~same_p   # truncate, then append
        need_append = (act & ~in_log) | diverge
        room = ((idx - ns.snap_index) <= cfg.log_cap) & ~df
        do_append = need_append & room
        write_t.append(same_p | do_append)
        write_p.append(do_append)
        # Truncation (divergent suffix) is just lowering last_index in the
        # ring model; append then restores it to idx when there is room.
        last_index = jnp.where(
            do_append, idx,
            jnp.where(diverge & ~room, idx - 1, last_index))
        stopped = stopped | (need_append & ~room)
        hi = jnp.where(same_t | same_p | do_append, idx, hi)
    # Stage 2 — commit all decisions in ONE masked pass per array. Each
    # entry's ring slot is a per-node scalar from stage 1; the slots are
    # pairwise distinct, so the E one-hot masks compose with no ordering.
    # (No modulo over the lane axis here: TPU integer remainder is a
    # multi-op sequence, and an [L]-wide one measurably dominated the
    # whole tick when tried.)
    lanes = jnp.arange(cfg.log_cap, dtype=I32)
    t_mask = jnp.zeros((cfg.log_cap,), BOOL)
    p_mask = jnp.zeros((cfg.log_cap,), BOOL)
    t_val = jnp.zeros((cfg.log_cap,), I32)
    p_val = jnp.zeros((cfg.log_cap,), I32)
    for j in range(cfg.max_entries_per_msg):
        on_j = lanes == slots[j]
        t_mask = t_mask | (on_j & write_t[j])
        p_mask = p_mask | (on_j & write_p[j])
        t_val = jnp.where(on_j, ent_t[j], t_val)
        p_val = jnp.where(on_j, ent_p[j], p_val)
    log_term = jnp.where(t_mask, t_val, ns.log_term)
    log_payload = jnp.where(p_mask, p_val, ns.log_payload)

    commit = jnp.where(
        proceed & (m_commit > ns.commit),
        jnp.maximum(ns.commit, jnp.minimum(m_commit, hi)),
        ns.commit)
    ns = ns._replace(log_term=log_term, log_payload=log_payload,
                     last_index=last_index, commit=commit)

    match = jnp.where(
        past, last_index + 1,
        jnp.where(conflict, ci, jnp.where(proceed, hi, 0)))
    out = out._replace(
        ae_resp_present=_put(out.ae_resp_present, src, present, True),
        ae_resp_term=_put(out.ae_resp_term, src, present, ns.term),
        ae_resp_success=_put(out.ae_resp_success, src, present, proceed),
        ae_resp_match=_put(out.ae_resp_match, src, present, match),
    )
    return ns, out


def _on_ae_resp(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_ae_resp` (node.py:263)."""
    present = ib.ae_resp_present[src]
    m_term = ib.ae_resp_term[src]
    m_success = ib.ae_resp_success[src]
    m_match = ib.ae_resp_match[src]
    higher = present & (m_term > ns.term)
    ns = _step_down(cfg, ns, m_term, higher)
    cont = present & ~higher & (ns.role == LEADER) & (m_term == ns.term)
    if cfg.read_every:
        # Any current-term response is ReadIndex deference evidence
        # (node.py:339): stamp the arrival tick, success or not.
        ns = ns._replace(ack_time=ns.ack_time.at[src].set(
            jnp.where(cont, gl[2], ns.ack_time[src])))
    succ = cont & m_success
    fail = cont & ~m_success
    new_match = jnp.maximum(ns.match_index[src], m_match)
    match_index = ns.match_index.at[src].set(
        jnp.where(succ, new_match, ns.match_index[src]))
    next_index = ns.next_index.at[src].set(jnp.where(
        succ, new_match + 1,
        jnp.where(fail,
                  jnp.maximum(1, jnp.minimum(ns.next_index[src] - 1, m_match)),
                  ns.next_index[src])))
    return ns._replace(match_index=match_index, next_index=next_index), out


def _on_is_req(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_is_req` (node.py:275)."""
    present = ib.is_req_present[src]
    m_term = ib.is_req_term[src]
    m_si = ib.is_req_snap_index[src]
    m_st = ib.is_req_snap_term[src]
    m_sd = ib.is_req_snap_digest[src]
    m_sv = ib.is_req_snap_voters[src]
    ns = _step_down(cfg, ns, m_term, present & (m_term > ns.term))
    stale = present & (m_term < ns.term)
    ok = present & ~stale
    ns = _accept_leader(cfg, ns, g, i, src, ok, gl[2])
    have = ok & (m_si <= ns.commit)   # already covered (node.py:283)
    inst = ok & ~have
    # Keep-the-suffix test (node.py:288-293). In the ring model keeping the
    # suffix means last_index is simply left alone (slots are absolute).
    keep = (inst & (m_si <= ns.last_index) & (m_si >= ns.snap_index)
            & (_term_at(cfg, ns, jnp.maximum(m_si, ns.snap_index)) == m_st))
    sess = {}
    if cfg.clients_u32:
        # The snapshot's dedup table installs with the rest of the
        # snapshot state (node.py _on_is_req: snap_sessions from the
        # message, live sessions rebuilt from it).
        m_sess = ib.is_req_snap_sessions[src]
        sess = dict(session_seq=jnp.where(inst, m_sess, ns.session_seq),
                    snap_session_seq=jnp.where(inst, m_sess,
                                               ns.snap_session_seq))
    ns = ns._replace(
        last_index=jnp.where(inst, jnp.where(keep, ns.last_index, m_si),
                             ns.last_index),
        snap_index=jnp.where(inst, m_si, ns.snap_index),
        snap_term=jnp.where(inst, m_st, ns.snap_term),
        snap_digest=jnp.where(inst, m_sd, ns.snap_digest),
        snap_voters=jnp.where(inst, m_sv, ns.snap_voters),
        commit=jnp.where(inst, m_si, ns.commit),
        applied=jnp.where(inst, m_si, ns.applied),
        digest=jnp.where(inst, m_sd, ns.digest),
        **sess,
    )
    match = jnp.where(stale, 0, jnp.where(have, ns.commit, m_si))
    out = out._replace(
        is_resp_present=_put(out.is_resp_present, src, present, True),
        is_resp_term=_put(out.is_resp_term, src, present, ns.term),
        is_resp_match=_put(out.is_resp_match, src, present, match),
    )
    return ns, out


def _on_is_resp(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_is_resp` (node.py:305)."""
    present = ib.is_resp_present[src]
    m_term = ib.is_resp_term[src]
    m_match = ib.is_resp_match[src]
    higher = present & (m_term > ns.term)
    ns = _step_down(cfg, ns, m_term, higher)
    cont = present & ~higher & (ns.role == LEADER) & (m_term == ns.term)
    if cfg.read_every:
        ns = ns._replace(ack_time=ns.ack_time.at[src].set(
            jnp.where(cont, gl[2], ns.ack_time[src])))
    new_match = jnp.maximum(ns.match_index[src], m_match)
    match_index = ns.match_index.at[src].set(
        jnp.where(cont, new_match, ns.match_index[src]))
    next_index = ns.next_index.at[src].set(
        jnp.where(cont, new_match + 1, ns.next_index[src]))
    return ns._replace(match_index=match_index, next_index=next_index), out


def _start_election_masked(cfg, ns, out, g, i, cond, t):
    """`Node._start_election` under a mask: term bump, candidacy, fresh
    timer draw, instant single-voter win, RequestVote broadcast. Shared
    by the pre-vote quorum path (phase D) and phase T's skip case."""
    ns = ns._replace(
        term=jnp.where(cond, ns.term + 1, ns.term),
        role=jnp.where(cond, CANDIDATE, ns.role),
        voted_for=jnp.where(cond, i, ns.voted_for),
        leader_id=jnp.where(cond, NO_VOTE, ns.leader_id),
        votes=jnp.where(cond, jnp.arange(cfg.k) == i, ns.votes),
    )
    ns = _reset_timer(cfg, ns, g, i, cond, t)
    won = cond & _vote_quorum(cfg, ns, ns.votes)   # instant single-voter win
    ns = _become_leader(cfg, ns, i, won)
    llt = _last_log_term(cfg, ns)
    for p in range(cfg.k):
        send = cond & ~won & (i != p)
        out = out._replace(
            rv_req_present=_put(out.rv_req_present, p, send, True),
            rv_req_term=_put(out.rv_req_term, p, send, ns.term),
            rv_req_lli=_put(out.rv_req_lli, p, send, ns.last_index),
            rv_req_llt=_put(out.rv_req_llt, p, send, llt),
        )
    return ns, out


def _on_pv_req(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_pv_req`: non-binding pre-vote grant — proposed term
    ahead, log up-to-date, not the leader, lease expired. No term
    adoption, no voted_for, no timer reset."""
    if not cfg.prevote:
        return ns, out
    present = ib.pv_req_present[src]
    m_term, m_lli, m_llt = (ib.pv_req_term[src], ib.pv_req_lli[src],
                            ib.pv_req_llt[src])
    llt = _last_log_term(cfg, ns)
    log_ok = (m_llt > llt) | ((m_llt == llt) & (m_lli >= ns.last_index))
    grant = (present & (m_term > ns.term) & log_ok & (ns.role != LEADER)
             & (ns.leader_elapsed >= cfg.election_min))
    out = out._replace(
        pv_resp_present=_put(out.pv_resp_present, src, present, True),
        pv_resp_term=_put(out.pv_resp_term, src, present, ns.term),
        pv_resp_req_term=_put(out.pv_resp_req_term, src, present, m_term),
        pv_resp_granted=_put(out.pv_resp_granted, src, present, grant),
    )
    return ns, out


def _on_pv_resp(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_pv_resp`: tally pre-votes; a quorum starts the REAL
    election (term bump + RequestVote broadcast) right here in phase D,
    exactly as the CPU oracle's `_start_election` call does."""
    if not cfg.prevote:
        return ns, out
    present = ib.pv_resp_present[src]
    m_term = ib.pv_resp_term[src]
    m_req = ib.pv_resp_req_term[src]
    m_granted = ib.pv_resp_granted[src]
    higher = present & (m_term > ns.term)
    ns = _step_down(cfg, ns, m_term, higher)
    cont = (present & ~higher & (ns.role == PRECANDIDATE)
            & (m_req == ns.term + 1) & m_granted)
    votes = ns.votes.at[src].set(ns.votes[src] | cont)
    ns = ns._replace(votes=votes)
    won_pre = cont & _vote_quorum(cfg, ns, votes)
    return _start_election_masked(cfg, ns, out, g, i, won_pre, gl[2])


def _on_tn_req(cfg, ns, out, g, i, src: int, ib: Mailbox, gl):
    """`Node._on_tn_req`: TimeoutNow — campaign immediately, bypassing
    PreVote (the handoff is deliberate; see node.py)."""
    if not cfg.transfer_u32:
        return ns, out
    present = ib.tn_present[src]
    m_term = ib.tn_term[src]
    ns = _step_down(cfg, ns, m_term, present & (m_term > ns.term))
    # FOLLOWER/PRECANDIDATE only (node.py): a CANDIDATE already
    # campaigned — possibly this very tick via a pre-ballot quorum — and
    # a second start would double-write the per-(type,src,dst) RV slot.
    cond = (present & (m_term == ns.term)
            & (ns.role != LEADER) & (ns.role != CANDIDATE))
    if cfg.reconfig_u32:
        voters, _ = _current_config(cfg, ns)
        cond = cond & (((voters >> i) & 1) == 1)
    return _start_election_masked(cfg, ns, out, g, i, cond, gl[2])


_HANDLERS = (_on_rv_req, _on_rv_resp, _on_ae_req, _on_ae_resp,
             _on_is_req, _on_is_resp, _on_pv_req, _on_pv_resp, _on_tn_req)
#             canonical rpc type order (PV/TN last — rpc.py)


# ----------------------------------------------------------------- phase T


def _phase_t(cfg, ns, out, g, i, t):
    """`Node.phase_t` (node.py:316) + `_broadcast_append` (node.py:327)
    + `_start_election` (node.py:122) + the scheduled leadership
    transfer (node.py `_maybe_transfer`). `t` is the absolute tick (the
    transfer schedule hashes it)."""
    is_leader = ns.role == LEADER
    hb = ns.heartbeat_elapsed + 1
    fire = is_leader & (hb >= cfg.heartbeat_every)
    ns = ns._replace(heartbeat_elapsed=jnp.where(
        is_leader, jnp.where(fire, 0, hb), ns.heartbeat_elapsed))

    for p in range(cfg.k):
        cond = fire & (i != p)
        use_is = cond & (ns.next_index[p] <= ns.snap_index)
        use_ae = cond & (ns.next_index[p] > ns.snap_index)
        out = out._replace(
            is_req_present=_put(out.is_req_present, p, use_is, True),
            is_req_term=_put(out.is_req_term, p, use_is, ns.term),
            is_req_snap_index=_put(out.is_req_snap_index, p, use_is,
                                   ns.snap_index),
            is_req_snap_term=_put(out.is_req_snap_term, p, use_is,
                                  ns.snap_term),
            is_req_snap_digest=_put(out.is_req_snap_digest, p, use_is,
                                    ns.snap_digest),
            is_req_snap_voters=_put(out.is_req_snap_voters, p, use_is,
                                    ns.snap_voters),
        )
        if cfg.clients_u32:
            out = out._replace(is_req_snap_sessions=_put(
                out.is_req_snap_sessions, p, use_is, ns.snap_session_seq))
        # No entry gather: the receiver pulls (prev, prev+n] out of this
        # sender's ring at delivery time (see Mailbox docstring) — the
        # send-side gather loop this replaces was the hottest op group
        # in the whole tick (DESIGN.md §7).
        prev = ns.next_index[p] - 1
        n = jnp.minimum(cfg.max_entries_per_msg, ns.last_index - prev)
        out = out._replace(
            ae_req_present=_put(out.ae_req_present, p, use_ae, True),
            ae_req_term=_put(out.ae_req_term, p, use_ae, ns.term),
            ae_req_prev_index=_put(out.ae_req_prev_index, p, use_ae, prev),
            ae_req_prev_term=_put(out.ae_req_prev_term, p, use_ae,
                                  _term_at(cfg, ns, prev)),
            ae_req_n=_put(out.ae_req_n, p, use_ae, n),
            ae_req_commit=_put(out.ae_req_commit, p, use_ae, ns.commit),
        )

    if cfg.transfer_u32:
        # `Node._maybe_transfer` (DESIGN.md §2d): first tick of a firing
        # epoch, hash-chosen target, gated on current-config voter +
        # fully caught up. The destination is traced, so the send is a
        # K-unrolled one-hot write.
        epoch = t // cfg.transfer_epoch
        attempts = (is_leader & ((t % cfg.transfer_epoch) == 0)
                    & jrng.transfer_fires(cfg.seed, g, epoch,
                                          cfg.transfer_u32))
        target = jrng.transfer_target(cfg.seed, g, epoch, cfg.k)
        # Gate (node.py _send_timeout_now): most-caught-up peer holding
        # every committed entry. The self slot of match_index is always
        # 0, so the max ranges over peers only.
        mt = _lget(ns.match_index, target)
        caught_up = (mt >= ns.commit) & (mt == jnp.max(ns.match_index, -1))
        ok = attempts & caught_up & (target != i)
        if cfg.reconfig_u32:
            votersT, _ = _current_config(cfg, ns)
            ok = ok & (((votersT >> target) & 1) == 1)
        for p in range(cfg.k):
            send = ok & (target == p)
            out = out._replace(
                tn_present=_put(out.tn_present, p, send, True),
                tn_term=_put(out.tn_term, p, send, ns.term),
            )

    # Election timeout (non-leaders; non-voters never campaign —
    # node.py phase_t's is_voter gate). With reconfig statically off,
    # everyone is a voter and the gate vanishes. The PreVote lease clock
    # follows node.py phase_t: leaders zero it, everyone else counts up.
    ee = ns.election_elapsed + 1
    timeout = ~is_leader & (ee >= ns.deadline)
    if cfg.reconfig_u32:
        voters0, _ = _current_config(cfg, ns)
        timeout = timeout & (((voters0 >> i) & 1) == 1)
    ns = ns._replace(
        election_elapsed=jnp.where(is_leader, ns.election_elapsed, ee),
        leader_elapsed=jnp.where(is_leader, 0, ns.leader_elapsed + 1))
    if cfg.prevote:
        # `Node._start_prevote`: pre-candidacy, no term bump; the
        # single-voter config skips straight to the real election
        # (matching the CPU's nested `_start_election` call, including
        # its second deadline draw).
        ns = ns._replace(
            role=jnp.where(timeout, PRECANDIDATE, ns.role),
            leader_id=jnp.where(timeout, NO_VOTE, ns.leader_id),
            votes=jnp.where(timeout, jnp.arange(cfg.k) == i, ns.votes),
        )
        ns = _reset_timer(cfg, ns, g, i, timeout, t)
        skip = timeout & _vote_quorum(cfg, ns, ns.votes)
        ns, out = _start_election_masked(cfg, ns, out, g, i, skip, t)
        llt = _last_log_term(cfg, ns)
        for p in range(cfg.k):
            send = timeout & ~skip & (i != p)
            out = out._replace(
                pv_req_present=_put(out.pv_req_present, p, send, True),
                pv_req_term=_put(out.pv_req_term, p, send, ns.term + 1),
                pv_req_lli=_put(out.pv_req_lli, p, send, ns.last_index),
                pv_req_llt=_put(out.pv_req_llt, p, send, llt),
            )
        return ns, out
    return _start_election_masked(cfg, ns, out, g, i, timeout, t)


# ----------------------------------------------------------------- phase C


def _phase_c(cfg, ns, g, i, t, csub=None, cpay=None):
    """`Node.phase_c`: scheduled read registration (DESIGN.md §2c),
    scheduled membership proposal (DESIGN.md §2b), then open-loop
    client session appends (DESIGN.md §10 — `csub`/`cpay` are the
    [S] submit pulses and payloads raised by the PREVIOUS tick's
    client transition; None with clients off), then fire-hose command
    appends. A disk-full leader (r20, DESIGN.md §19) appends nothing —
    every site below folds the pressure mask into its room check, the
    batched form of the oracle's `_append` budget gate."""
    lead = ns.role == LEADER
    df = jnp.zeros((), BOOL)
    if cfg.nem_disk:
        df = jrng.nem_disk_full(cfg.seed, cfg.nem_disk, g, i, t, cfg.k)

    if cfg.read_every:
        # `Node._maybe_schedule_read`: START of phase C, so the read
        # point is the pre-append commit index; gated like read_begin.
        gate = ((ns.commit == ns.last_index)
                | (_term_at(cfg, ns, ns.commit) == ns.term))
        reg = (lead & ((t % cfg.read_every) == 0)
               & (ns.sched_read_index < 0) & gate)
        ns = ns._replace(
            sched_read_index=jnp.where(reg, ns.commit, ns.sched_read_index),
            sched_read_reg=jnp.where(reg, t, ns.sched_read_reg),
        )

    if cfg.reconfig_u32:
        # `Node._maybe_propose_reconfig`: first tick of a firing epoch.
        epoch = t // cfg.reconfig_epoch
        fires = ((t % cfg.reconfig_epoch) == 0) & jrng.reconfig_fires(
            cfg.seed, g, epoch, cfg.reconfig_u32)
        target = jrng.reconfig_target(cfg.seed, g, epoch, cfg.k)
        voters, cfg_index = _current_config(cfg, ns)
        new_mask = voters ^ jnp.left_shift(jnp.int32(1), target)
        gate = ((quorum.popcount(new_mask) >= cfg.effective_min_voters)
                & (cfg_index <= ns.commit)
                & (_term_at(cfg, ns, ns.commit) == ns.term))
        idx = ns.last_index + 1
        room = ((idx - ns.snap_index) <= cfg.log_cap) & ~df
        do = lead & fires & gate & room
        s = _slot(cfg, idx)
        ns = ns._replace(
            log_term=_lset(ns.log_term, s, do, ns.term),
            log_payload=_lset(ns.log_payload, s, do,
                              jnp.int32(CONFIG_FLAG) | new_mask),
            last_index=jnp.where(do, idx, ns.last_index),
        )

    last_index = ns.last_index
    log_term, log_payload = ns.log_term, ns.log_payload
    stopped = jnp.zeros((), BOOL)
    if cfg.clients_u32:
        # EVERY node that believes itself leader appends the pulsed
        # session ops, in slot order, stopping at window-full (the
        # oracle's `phase_c(client_cmds)` break). Duplicate appends by
        # transient dual leaders are safe by the exactly-once fold.
        for sl in range(cfg.client_slots):
            idx = last_index + 1
            room = ((idx - ns.snap_index) <= cfg.log_cap) & ~df
            want = lead & (csub[sl] != 0)
            do = want & room & ~stopped
            s = _slot(cfg, idx)
            log_term = _lset(log_term, s, do, ns.term)
            log_payload = _lset(log_payload, s, do, cpay[sl])
            last_index = jnp.where(do, idx, last_index)
            stopped = stopped | (want & ~room)
    for _ in range(cfg.cmds_per_tick):
        idx = last_index + 1
        room = ((idx - ns.snap_index) <= cfg.log_cap) & ~df
        do = lead & room & ~stopped
        payload = jrng.client_payload(cfg.seed, g, ns.term, idx)
        s = _slot(cfg, idx)
        log_term = _lset(log_term, s, do, ns.term)
        log_payload = _lset(log_payload, s, do, payload)
        last_index = jnp.where(do, idx, last_index)
        stopped = stopped | (lead & ~room)
    return ns._replace(last_index=last_index, log_term=log_term,
                       log_payload=log_payload)


# ----------------------------------------------------------------- phase A


def _phase_a(cfg, ns, g, i, t):
    """`Node.phase_a`: voters-aware commit advance, removed-leader
    step-down, apply, compact. `g`/`t` feed only the statically-gated
    compaction-pressure clauses (r20, DESIGN.md §19)."""
    if cfg.reconfig_u32 == 0:
        # Static fast path: full config, compile-time majority; the
        # removed-leader demotion branch cannot fire and is elided.
        n = quorum.commit_candidate(ns.match_index, ns.last_index, i,
                                    cfg.k, cfg.majority)
    else:
        voters, cfg_index = _current_config(cfg, ns)
        n = quorum.commit_candidate_voters(ns.match_index, ns.last_index, i,
                                           voters, cfg.k)
    # §5.4.2: current-term entries only. n > commit >= snap_index makes the
    # term_at read valid under the mask (n == -1 when no voters exist,
    # which the n > commit guard also rejects).
    advance = ((ns.role == LEADER) & (n > ns.commit)
               & (_term_at(cfg, ns, n) == ns.term))
    commit = jnp.where(advance, n, ns.commit)

    if cfg.reconfig_u32:
        # A removed leader steps down once its removal is committed
        # (node.py phase_a): latest config entry committed, self not in it.
        self_voter = ((voters >> i) & 1) == 1
        demote = (ns.role == LEADER) & (cfg_index <= commit) & ~self_voter
        ns = ns._replace(
            role=jnp.where(demote, FOLLOWER, ns.role),
            leader_id=jnp.where(demote, NO_VOTE, ns.leader_id),
            votes=jnp.where(demote, False, ns.votes),
        )
        ns = _drop_reads(cfg, ns, demote)

    # Apply loop: commit - applied <= L by the window invariant, so an
    # L-step unrolled chain covers it. The digest chain is inherently
    # sequential (node.py:369-374). With scheduled clients on, the
    # exactly-once filter (node.py `_session_effective`, scheduled
    # form) runs at digest-fold time: sids are pre-registered 0..S-1
    # and REGISTER entries cannot occur in a scheduled universe, so
    # "sid unknown" == sid >= S; a session command folds — and
    # advances the dedup table — iff its seq strictly advances the
    # sid's entry. The table IS the dedup decision record.
    applied, digest = ns.applied, ns.digest
    table = ns.session_seq
    for _ in range(cfg.log_cap):
        idx = applied + 1
        act = idx <= commit
        p = _payload_at(cfg, ns, idx)
        if cfg.clients_u32:
            is_sess = ((p & SESSION_FLAG) != 0) & ((p & CONFIG_FLAG) == 0)
            sid = (p >> SESSION_SID_SHIFT) & SESSION_SID_MASK
            seq = (p >> SESSION_SEQ_SHIFT) & SESSION_SEQ_MASK
            cur = _lget(table, sid)
            eff_sess = is_sess & (sid < cfg.client_slots) & (seq > cur)
            table = _lset(table, sid, act & eff_sess, seq)
            fold = act & (~is_sess | eff_sess)
        else:
            fold = act
        digest = jnp.where(fold, jrng.digest_update(digest, idx, p), digest)
        applied = jnp.where(act, idx, applied)

    compact = (commit - ns.snap_index) >= cfg.compact_every
    if cfg.nem_compact:
        # Compaction pressure (r20, DESIGN.md §19): a blocked node's
        # snapshot step is delayed; the log_cap ring genuinely fills
        # and the append-site room checks become the runtime
        # backpressure path that throttles replication.
        compact = compact & ~jrng.nem_compact_block(
            cfg.seed, cfg.nem_compact, g, i, t)
    sess = {}
    if cfg.clients_u32:
        # Compaction folds the live table into the snapshot (node.py
        # phase_a: `snap_sessions = dict(sessions)`).
        sess = dict(session_seq=table,
                    snap_session_seq=jnp.where(compact, table,
                                               ns.snap_session_seq))
    ns = ns._replace(
        commit=commit, applied=applied, digest=digest, **sess,
        snap_term=jnp.where(compact, _term_at(cfg, ns, commit), ns.snap_term),
        snap_voters=jnp.where(compact, _committed_voters(cfg, ns, commit),
                              ns.snap_voters),
        snap_index=jnp.where(compact, commit, ns.snap_index),
        snap_digest=jnp.where(compact, digest, ns.snap_digest),
    )

    if cfg.read_every:
        # Scheduled-read completion (node.py phase_a end): voters-aware
        # ReadIndex quorum over the ack evidence; a step-down or demotion
        # earlier this tick already cleared the pending read.
        sched = ns.sched_read_index >= 0
        lanes = jnp.arange(cfg.k, dtype=I32)
        recent = ns.ack_time >= ns.sched_read_reg + 2
        if cfg.reconfig_u32 == 0:
            voter_lane = jnp.ones((cfg.k,), BOOL)
            self_voter = jnp.ones((), I32)
            maj = cfg.majority
        else:
            voters2, _ = _current_config(cfg, ns)
            voter_lane = quorum.voter_bits(voters2, cfg.k)
            self_voter = (voters2 >> i) & 1
            maj = quorum.voter_majority(voters2)
        acks = jnp.sum((recent & voter_lane & (lanes != i)).astype(I32), -1)
        done = (sched & (acks + self_voter >= maj)
                & (ns.applied >= ns.sched_read_index))
        ns = ns._replace(
            reads_done=ns.reads_done + done.astype(I32),
            sched_read_index=jnp.where(done, -1, ns.sched_read_index),
        )
    return ns


# ------------------------------------------------------------ per-node tick


def _node_tick(cfg, t, ns: PerNode, inbox: Mailbox, g, i, glog_t, glog_p,
               csub=None, cpay=None):
    """One node's full D/T/C/A tick. `inbox` leaves lead with [K_src];
    the returned outbox leaves lead with [K_dst]. `t` is the absolute
    tick (the reconfig schedule hashes it). `glog_t`/`glog_p` are the
    whole GROUP's end-of-previous-tick log rings `[K, L]`, broadcast
    across the node axis — the receiver-pull source for AppendEntries.

    `i` is TRACED (the vmapped node lane): a variant with a static
    Python `i` and the node axis unrolled — deleting the provable no-op
    src==i handler applications — was tried and measured WORSE (21.4 vs
    15.4 ms/tick at 100K groups, 5x the compile time): [G]-shaped ops
    lose more to per-op overhead and lost cross-node fusion than the
    skipped fifth of phase D saves. Keep the [G, K] double-vmap."""
    out = empty_mailbox((cfg.k,), cfg.prevote, cfg.transfer_u32 != 0,
                        cfg.client_slots if cfg.clients_u32 else 0)
    gl = (glog_t, glog_p, t)   # phase-D context: group logs + the clock
    # Phase D: canonical (type, src) order — node.py:154 + rpc.sort_inbox.
    for handler in _HANDLERS:
        for src in range(cfg.k):
            ns, out = handler(cfg, ns, out, g, i, src, inbox, gl)
    ns, out = _phase_t(cfg, ns, out, g, i, t)
    ns = _phase_c(cfg, ns, g, i, t, csub, cpay)
    ns = _phase_a(cfg, ns, g, i, t)
    return ns, out


# ------------------------------------------------------------- global tick


def _apply_restart(cfg, nodes: PerNode, g_grid, i_grid, edge, t):
    """`Node.restart` (node.py:139): durable survives, volatile rewinds.
    `t` feeds only the statically-gated nemesis clock-skew clauses."""
    new_deadline = jrng.election_deadline(cfg.seed, g_grid, i_grid,
                                          nodes.rng_draws, cfg.election_min,
                                          cfg.election_range)
    if cfg.nem_skew:
        new_deadline = jnp.maximum(1, new_deadline + jrng.nem_deadline_extra(
            cfg.seed, cfg.nem_skew, g_grid, i_grid, t))
    e1 = edge[..., None]
    return nodes._replace(
        role=jnp.where(edge, FOLLOWER, nodes.role),
        leader_id=jnp.where(edge, NO_VOTE, nodes.leader_id),
        commit=jnp.where(edge, nodes.snap_index, nodes.commit),
        applied=jnp.where(edge, nodes.snap_index, nodes.applied),
        digest=jnp.where(edge, nodes.snap_digest, nodes.digest),
        votes=jnp.where(e1, False, nodes.votes),
        next_index=jnp.where(e1, 1, nodes.next_index),
        match_index=jnp.where(e1, 0, nodes.match_index),
        heartbeat_elapsed=jnp.where(edge, 0, nodes.heartbeat_elapsed),
        election_elapsed=jnp.where(edge, 0, nodes.election_elapsed),
        leader_elapsed=jnp.where(edge, 0, nodes.leader_elapsed),
        deadline=jnp.where(edge, new_deadline, nodes.deadline),
        rng_draws=nodes.rng_draws + edge.astype(I32),
        # Scheduled-read state: restart drops client state and zeroes
        # the volatile reads_done counter (node.py restart).
        ack_time=jnp.where(e1, -1, nodes.ack_time),
        sched_read_index=jnp.where(edge, -1, nodes.sched_read_index),
        reads_done=jnp.where(edge, 0, nodes.reads_done),
        # The live dedup table is pure state-machine state: restart
        # rewinds it to the snapshot table, like digest (node.py
        # restart: `sessions = dict(snap_sessions)`).
        **({"session_seq": jnp.where(e1, nodes.snap_session_seq,
                                     nodes.session_seq)}
           if cfg.clients_u32 else {}),
    )


def _filter_mailbox(cfg, mb: Mailbox, t, alive_now, group_id) -> Mailbox:
    """`Transport.deliver`'s fault filter (transport.py:35): dead
    destinations, partitioned links, dropped links. Mailbox layout is
    [G, dst, src] (see `tick`)."""
    g, k = alive_now.shape
    gg = group_id[:, None, None]
    dst = jnp.arange(k, dtype=I32)[None, :, None]
    src = jnp.arange(k, dtype=I32)[None, None, :]
    part = jrng.link_partitioned(cfg.seed, gg, t, src, dst,
                                 cfg.partition_u32, cfg.partition_epoch)
    drop = jrng.link_dropped(cfg.seed, gg, t, src, dst, cfg.drop_u32)
    keep = alive_now[:, :, None] & ~part & ~drop
    if cfg.nem_link:
        # Nemesis link clauses (DESIGN.md §14) AND into the same
        # delivery filter as the base drop/partition schedules.
        keep = keep & jrng.nem_link_ok(cfg.seed, cfg.nem_link, gg, t,
                                       src, dst, cfg.k)
    pv = {}
    if mb.pv_req_present is not None:
        pv = dict(pv_req_present=mb.pv_req_present & keep,
                  pv_resp_present=mb.pv_resp_present & keep)
    if mb.tn_present is not None:
        pv["tn_present"] = mb.tn_present & keep
    return mb._replace(
        rv_req_present=mb.rv_req_present & keep,
        rv_resp_present=mb.rv_resp_present & keep,
        ae_req_present=mb.ae_req_present & keep,
        ae_resp_present=mb.ae_resp_present & keep,
        is_req_present=mb.is_req_present & keep,
        is_resp_present=mb.is_resp_present & keep,
        **pv,
    )


@functools.partial(jax.jit, static_argnums=0)
def tick(cfg: RaftConfig, st: State, t) -> State:
    """One global tick over all [G, K] replicas: `Cluster.tick`
    (cluster.py:100) vectorized. `t` is the absolute tick counter (traced;
    fault schedules hash it).

    Narrow-native boundary (DESIGN.md §18): when any `narrow_*` dial is
    on, the resident carry is the narrow form — the body widens every
    narrowed lane back to the audited i32 widths on entry, computes the
    UNCHANGED wide tick, and re-narrows on exit (latching group_id bit
    31 on overflow). Dtype-stable for lax.scan by construction, and the
    wide compute means tick semantics are byte-for-byte the r18 ones on
    every engine — the dials move bytes, never logic."""
    from raft_tpu.sim import state as state_mod
    narrowing = state_mod.narrow_active(cfg)
    if narrowing:
        st = state_mod.widen_state(cfg, st)
    out = _tick_wide(cfg, st, t)
    if narrowing:
        out = state_mod.narrow_state(cfg, out)
    return out


def _tick_wide(cfg: RaftConfig, st: State, t) -> State:
    """The wide-i32 tick body — everything below this line is r18's
    tick, untouched by the narrow dials."""
    g, k = st.alive_prev.shape
    g_grid = jnp.broadcast_to(st.group_id[:, None], (g, k))
    i_grid = jnp.broadcast_to(jnp.arange(k, dtype=I32)[None, :], (g, k))

    alive_now = jnp.broadcast_to(
        jrng.node_alive(cfg.seed, g_grid, i_grid, t,
                        cfg.crash_u32, cfg.crash_epoch), (g, k))
    if cfg.nem_crash:
        # Nemesis crash-storm clauses AND into the base crash schedule
        # (a node is up only when BOTH schedules say so).
        alive_now = alive_now & jrng.nem_alive(cfg.seed, cfg.nem_crash,
                                               g_grid, i_grid, t)
    nodes = _apply_restart(cfg, st.nodes, g_grid, i_grid,
                           alive_now & ~st.alive_prev, t)

    # The mailbox lives in [G, dst, src, ...] layout: that is what the
    # per-node slice consumes directly (each node sees its per-sender
    # inbox), and the stacks below put each node's [K_dst] outbox with
    # the sender on axis 2 — producing the same [G, dst, src] layout
    # with no whole-mailbox transpose between ticks.
    inbox = _filter_mailbox(cfg, st.mailbox, t, alive_now, st.group_id)

    csub = cpay = None
    if cfg.clients_u32:
        # The submit pulses raised by the PREVIOUS tick's client
        # transition, with their payloads ([G, S] each; broadcast to
        # every node in the group — a client talks to whoever claims
        # leadership).
        from raft_tpu.clients import workload
        scol = jnp.arange(cfg.client_slots, dtype=I32)[None, :]
        csub, cpay = workload.submit_payloads(cfg, st.clients,
                                              st.group_id[:, None], scol)

    node_fn = functools.partial(_node_tick, cfg, t)
    new_nodes, outbox = jax.vmap(
        jax.vmap(node_fn, in_axes=(0, 0, 0, 0, None, None, None, None),
                 out_axes=(0, 1)))(
        nodes, inbox, g_grid, i_grid, nodes.log_term, nodes.log_payload,
        csub, cpay)

    # Dead nodes: state frozen, sends erased (cluster.py:103-119 runs no
    # phase for them; transport keeps their in-flight mail).
    def freeze(new, old):
        m = alive_now.reshape(alive_now.shape + (1,) * (new.ndim - 2))
        return jnp.where(m, new, old)

    new_nodes = jax.tree.map(freeze, new_nodes, nodes)
    src_alive = alive_now[:, None, :]   # sender axis is 2 in [G, dst, src]
    pv = {}
    if outbox.pv_req_present is not None:
        pv = dict(pv_req_present=outbox.pv_req_present & src_alive,
                  pv_resp_present=outbox.pv_resp_present & src_alive)
    if outbox.tn_present is not None:
        pv["tn_present"] = outbox.tn_present & src_alive
    outbox = outbox._replace(
        rv_req_present=outbox.rv_req_present & src_alive,
        rv_resp_present=outbox.rv_resp_present & src_alive,
        ae_req_present=outbox.ae_req_present & src_alive,
        ae_resp_present=outbox.ae_resp_present & src_alive,
        is_req_present=outbox.is_req_present & src_alive,
        is_resp_present=outbox.is_resp_present & src_alive,
        **pv,
    )
    clients = st.clients
    if cfg.clients_u32:
        # Client transition on the POST-tick (post-freeze) state: acks
        # come from the group's applied dedup tables, next tick's
        # submit pulses are raised (clients/workload.py).
        from raft_tpu.clients import workload
        tmax = workload.table_max(new_nodes.session_seq, node_axis=1)
        clients = workload.client_update(
            cfg, clients, tmax, st.group_id[:, None],
            jnp.arange(cfg.client_slots, dtype=I32)[None, :], t)
    return State(nodes=new_nodes, mailbox=outbox, alive_prev=alive_now,
                 group_id=st.group_id, clients=clients)
