"""Host-side checkpoint of the batched simulator (SURVEY.md §5,
elastic recovery / checkpoint row).

A `State` is a pytree of dense arrays and the simulation is a pure
function of `(cfg, state, t)`, so a checkpoint is just the flattened
pytree plus the absolute tick counter: save both, reload in any process
(same cfg), continue from `t` — bit-identical to a run that never
stopped (`tests/test_checkpoint.py`). Metrics ride along optionally so a
resumed benchmark keeps its histograms.

Format: a single `.npz` with dot-separated field paths as keys and two
metadata scalars (`__tick__`, `__version__`). Everything is numpy on the
way out, `jnp` on the way in — no pickling, no host objects.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.clients.state import ClientState
from raft_tpu.config import RaftConfig
from raft_tpu.sim.run import Metrics
from raft_tpu.sim.state import Mailbox, PerNode, State

_VERSION = 1

# Metric leaves with a leading [G] axis — these follow the State's
# sharding on load; the scalars and the global [H] histograms replicate
# (discriminated by NAME, not shape: at G == HIST_SIZE a shape test
# would shard the histogram by accident).
_PER_GROUP_METRICS = ("committed", "leaderless", "safety",
                      "client_acked", "client_retries")


def _shard_metrics(metrics: Metrics, sharding) -> Metrics:
    """Reshard loaded metrics like the State: per-group leaves onto the
    mesh, the rest replicated (absent client lanes stay None). Only
    NamedShardings carry a mesh to replicate over; any other placement
    is applied to the State alone."""
    from jax.sharding import NamedSharding, PartitionSpec
    if not isinstance(sharding, NamedSharding):
        return metrics
    rep = NamedSharding(sharding.mesh, PartitionSpec())
    return Metrics(**{
        f: (None if getattr(metrics, f) is None else
            jax.device_put(getattr(metrics, f),
                           sharding if f in _PER_GROUP_METRICS else rep))
        for f in Metrics._fields})


def iter_named_leaves(tree, prefix: str = ""):
    """(dot-path, leaf) over a NamedTuple pytree, skipping None
    subtrees (empty pytree slots, e.g. Mailbox pv_* with prevote off).
    THE naming rule for checkpoint keys — the engine-contract auditor
    (raft_tpu/analysis) walks with this same function so its leaf
    names can never drift from the npz keys `save` writes."""
    if tree is None:
        return
    if hasattr(tree, "_fields"):   # NamedTuple node
        for f in tree._fields:
            yield from iter_named_leaves(getattr(tree, f), f"{prefix}{f}.")
    else:
        yield prefix[:-1], tree


def _flatten(prefix: str, obj, out: dict):
    for name, leaf in iter_named_leaves(obj, prefix):
        out[name] = np.asarray(leaf)


def save(path, st: State, t: int, metrics: Optional[Metrics] = None,
         cfg: Optional[RaftConfig] = None) -> None:
    """Write `st` (+ optional metrics) and the absolute tick `t` to `path`.

    Pass `cfg` to embed the semantic config: `load` then refuses to resume
    under a different one (same shapes, different seed/fault knobs would
    silently continue the wrong universe otherwise)."""
    flat: dict = {"__version__": np.int64(_VERSION), "__tick__": np.int64(t)}
    if cfg is not None:
        # Narrow-native host boundary (DESIGN.md §18): a latched state
        # is invalid — refuse to persist it rather than freeze silent
        # truncation into a file.
        from raft_tpu.sim import state as state_mod
        state_mod.check_narrow_overflow(cfg, st)
        flat["__cfg__"] = np.bytes_(
            json.dumps(dataclasses.asdict(cfg), sort_keys=True))
    _flatten("state.", st, flat)
    if metrics is not None:
        _flatten("metrics.", metrics, flat)
    np.savez(path, **flat)


def _optional_fields(cls) -> frozenset:
    """Fields whose NamedTuple default is None — statically-gated
    subtrees `_flatten` legitimately skips on save: the prevote /
    transfer / session mailbox slots, PerNode's session tables."""
    return frozenset(f for f in cls._fields
                     if cls._field_defaults.get(f, "required") is None)


OPTIONAL_FIELDS = _optional_fields(Mailbox)   # kept for callers


def _load_nt(z, prefix: str, cls):
    """Legitimately-optional fields (`_optional_fields` — absent when
    their feature is off and skipped by `_flatten` on save, including
    every pre-r09 file's session leaves) load as None; any OTHER
    missing field is a corrupt/incompatible checkpoint and raises
    immediately, naming the field."""
    optional = _optional_fields(cls)

    def get(f):
        key = f"{prefix}{f}"
        if key not in z.files:
            if f in optional:
                return None
            raise KeyError(f"checkpoint missing field {key!r}")
        return jnp.asarray(z[key])
    return cls(**{f: get(f) for f in cls._fields})


def _hop_narrow(cfg: RaftConfig, st: State) -> State:
    """The narrow-axis hop (DESIGN.md §18): re-declare a loaded State at
    the cfg's resident dtypes, BY NAME, in both directions — a wide
    (incl. pre-r19) file narrows under a narrow cfg, a narrow file
    widens under a wide cfg. A leaf at a dtype that is neither the wide
    i32/u32/bool form nor the leaf's own narrow dtype
    (sim/state.full_narrow_spec) is a corrupt/incompatible file and
    refuses, naming the leaf; a wide value that does not FIT the target
    narrow dtype refuses too (the overflow latch fires on the hop)."""
    from raft_tpu.sim import state as state_mod
    allowed = state_mod.full_narrow_spec(cfg)

    def leaf(name, a):
        if a.dtype in (jnp.int32, jnp.uint32, jnp.bool_):
            return a
        dt = allowed.get(name)
        if dt is not None and a.dtype == dt:
            return a.astype(jnp.int32)   # exact: zero/sign-extend
        raise ValueError(
            f"checkpoint leaf state.{name} has dtype {a.dtype}, which "
            f"is neither the wide form nor its narrow-native dtype "
            f"({dt}) — refusing the narrow-axis hop")

    wide = state_mod._map_named(st, "", leaf)
    out = state_mod.narrow_state(cfg, wide)
    # A wide file whose values outgrow the target narrow dtypes latches
    # on the hop — refuse at the boundary, like save does.
    state_mod.check_narrow_overflow(cfg, out)
    return out


def load(path, cfg: Optional[RaftConfig] = None, sharding=None
         ) -> Tuple[State, int, Optional[Metrics]]:
    """Read (state, tick, metrics-or-None) from `path`.

    If `cfg` is given and the checkpoint embeds one, they must match
    exactly — resuming a deterministic universe under different semantic
    knobs is always a bug.

    Pass `sharding` (a `NamedSharding`, e.g. `parallel.state_sharding
    (mesh)`) to place the state directly onto a device mesh — the
    elastic-recovery path: a checkpoint written by an n-device run
    resumes on an m-device mesh of any divisor of G, because the npz is
    device-layout-free and `State.group_id` travels with the shard
    (`tests/test_checkpoint.py::test_resume_onto_different_mesh`).
    Saved metrics reshard along: per-group leaves follow the state, the
    scalars/histogram replicate (the dryrun's 1-device-checkpoint ->
    n-device-mesh hop rides this path)."""
    with np.load(path) as z:
        version = int(z["__version__"])
        if version != _VERSION:
            raise ValueError(f"unsupported checkpoint version {version}")
        if cfg is not None and "__cfg__" in z.files:
            saved = json.loads(bytes(z["__cfg__"]).decode())
            want = json.loads(json.dumps(dataclasses.asdict(cfg)))
            # Fields added after the file was written load as their
            # defaults: a pre-r09 universe simply had no such feature,
            # so the default value IS its semantic config (the same
            # backfill rule as the r07 metrics.safety ones). The r14
            # `nemesis` knob rides this table too — a pre-r14 file
            # backfills to the empty program, so it resumes under a
            # nemesis-free cfg and REFUSES under a nemesis-on one
            # (different universe schedule; the program itself is
            # list-of-int-lists after the JSON round trip, which
            # RaftConfig.__post_init__ normalizes back to the hashable
            # tuple form — proven by the auditor's checkpoint pass).
            defaults = json.loads(json.dumps(
                dataclasses.asdict(RaftConfig())))
            for k, v in defaults.items():
                saved.setdefault(k, v)
            # Kernel wire-LAYOUT knobs (config.LAYOUT_FIELDS) never
            # change what any engine computes, and checkpoints store
            # the layout-free State pytree — a packed run may resume an
            # unpacked file (incl. every pre-r13 file) and vice versa,
            # so they are excluded from the semantic match. The r16
            # RESIDENCY knobs (config.STREAM_FIELDS) follow the same
            # rule: a streamed run may resume a resident-layout file
            # (incl. every pre-r16 file) and vice versa — paging only
            # moves where the wire lives between chunk launches.
            # The r19 narrow-native dials (config.NARROW_FIELDS) follow
            # the same rule again: the narrow form is a value-preserving
            # re-declaration of the same State (widen/narrow on load by
            # leaf NAME below), so a narrow run may resume a wide file
            # (incl. every pre-r19 file) and vice versa.
            from raft_tpu.config import (LAYOUT_FIELDS, NARROW_FIELDS,
                                         STREAM_FIELDS)
            for k in LAYOUT_FIELDS + STREAM_FIELDS + NARROW_FIELDS:
                saved.pop(k, None)
                want.pop(k, None)
            if saved != want:
                diff = {k: (saved.get(k), want.get(k))
                        for k in set(saved) | set(want)
                        if saved.get(k) != want.get(k)}
                raise ValueError(f"checkpoint cfg mismatch: {diff}")
        t = int(z["__tick__"])
        clients = None
        if "state.clients.done" in z.files:
            clients = _load_nt(z, "state.clients.", ClientState)
        st = State(
            nodes=_load_nt(z, "state.nodes.", PerNode),
            mailbox=_load_nt(z, "state.mailbox.", Mailbox),
            alive_prev=jnp.asarray(z["state.alive_prev"]),
            group_id=jnp.asarray(z["state.group_id"]),
            clients=clients,
        )
        if cfg is not None:
            # Hop the narrow axis both ways (no-op when the file's
            # dtypes already match the cfg's resident form).
            st = _hop_narrow(cfg, st)
        metrics = None
        if "metrics.committed" in z.files:
            md = {f: jnp.asarray(z[f"metrics.{f}"])
                  for f in Metrics._fields if f"metrics.{f}" in z.files}
            if "safety" not in md:
                # Pre-observability checkpoint: no per-tick safety bits
                # were folded, so the resumed run's AND starts clean.
                md["safety"] = jnp.ones_like(md["committed"])
            client_lanes = ("client_acked", "client_retries",
                            "client_hist", "client_max_lat")
            if clients is not None:
                # r09 backfill (same pattern as the r07 safety ones): a
                # client universe whose file predates the SLO lanes
                # resumes with fresh zeroed lanes — acked/retries are
                # idempotent recomputes from the client state, so only
                # pre-file latency history is (correctly) absent.
                md.setdefault("client_acked",
                              jnp.zeros_like(md["committed"]))
                md.setdefault("client_retries",
                              jnp.zeros_like(md["committed"]))
                md.setdefault("client_hist", jnp.zeros_like(md["hist"]))
                md.setdefault("client_max_lat",
                              jnp.zeros((), md["hist"].dtype))
            else:
                for f in client_lanes:
                    md.setdefault(f, None)
            missing = set(Metrics._fields) - set(md)
            if missing:
                raise KeyError(f"checkpoint missing metric field(s) "
                               f"{sorted(missing)}")
            metrics = Metrics(**md)
    if sharding is not None:
        st = jax.device_put(st, sharding)
        if metrics is not None:
            metrics = _shard_metrics(metrics, sharding)
    return st, t, metrics
