"""Struct-of-arrays state for the batched TPU path (DESIGN.md §5).

Every field of the CPU oracle's `Node` (core/node.py) becomes an array with
leading dims `[G, K]` (G = independent Raft groups, K = replicas per
group). Logs are **ring-addressed by absolute index**: the entry at
absolute index ``i`` lives in slot ``(i - 1) % L``. Because the window
invariant ``last_index - snap_index <= L`` holds (DESIGN.md §3), the
mapping is injective over the live window — so compaction and
InstallSnapshot's keep-the-suffix case move ``snap_index`` without any
data movement, and truncation is just lowering ``last_index``.

The in-memory `Transport` (core/transport.py) becomes the dense `Mailbox`:
one slot per (group, src, dst, message-type), exploiting the tick
contract's guarantee of at most one message per (type, src, dst) per tick
(DESIGN.md §2). `Mailbox` triples as the in-flight buffer (`[G, K, K]`
leading dims), a node's inbox (`[K_src]` after transpose + vmap), and a
node's outbox (`[K_dst]` inside the per-node step).

The observability layer (DESIGN.md §8) treats this State as its whole
read surface: the per-tick safety fold (sim/check.py `tick_safety`) and
the flight recorder's message-volume signal (obs/recorder.py, summing
the `*_present` occupancy bits below) are pure functions of a post-tick
State — adding a leaf here extends the triage/diff surface
automatically (utils/trees names leaves by pytree path).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp

from raft_tpu.config import RaftConfig
from raft_tpu.core.node import FOLLOWER, NO_VOTE
from raft_tpu.utils import jrng

I32 = jnp.int32
U32 = jnp.uint32
BOOL = jnp.bool_
I8 = jnp.int8
I16 = jnp.int16
U16 = jnp.uint16


class PerNode(NamedTuple):
    """Per-replica state; one leaf per `Node` attribute (core/node.py).

    Leading dims `[G, K]` in a full `State`; scalars / `[K]` / `[L]`
    inside the vmapped per-node step.
    """

    # Durable (survives crash/restart — node.py:36-43).
    term: jnp.ndarray         # i32
    voted_for: jnp.ndarray    # i32, NO_VOTE = -1
    snap_index: jnp.ndarray   # i32
    snap_term: jnp.ndarray    # i32
    snap_digest: jnp.ndarray  # u32
    snap_voters: jnp.ndarray  # i32 — voter bitmask as of the snapshot prefix
    rng_draws: jnp.ndarray    # i32 — monotone deadline-draw counter
    last_index: jnp.ndarray   # i32 (CPU: derived from len(log); explicit here)
    log_term: jnp.ndarray     # i32[L], ring slot (i-1) % L
    log_payload: jnp.ndarray  # i32[L]
    # Volatile (reset on restart — node.py:45-57).
    role: jnp.ndarray         # i32: FOLLOWER/CANDIDATE/LEADER
    leader_id: jnp.ndarray    # i32
    commit: jnp.ndarray       # i32
    applied: jnp.ndarray      # i32
    digest: jnp.ndarray       # u32 — state-machine hash chain
    votes: jnp.ndarray        # bool[K]
    next_index: jnp.ndarray   # i32[K]
    match_index: jnp.ndarray  # i32[K]
    election_elapsed: jnp.ndarray   # i32
    heartbeat_elapsed: jnp.ndarray  # i32
    deadline: jnp.ndarray     # i32
    leader_elapsed: jnp.ndarray     # i32 — PreVote lease clock (node.py)
    # Scheduled-read state (DESIGN.md §2c; node.py `sched_read` /
    # `ack_time` / `reads_done`). Always present for a stable trace
    # surface; all writes are statically gated on `cfg.read_every`.
    ack_time: jnp.ndarray           # i32[K] — last current-term resp tick
    sched_read_index: jnp.ndarray   # i32 — read point, -1 = none
    sched_read_reg: jnp.ndarray     # i32 — registration tick
    reads_done: jnp.ndarray         # i32 — completed linearizable reads
    # Exactly-once session dedup tables (DESIGN.md §10; node.py
    # `sessions` / `snap_sessions`) — present only when the scheduled
    # client traffic is on (cfg.clients_u32; None otherwise, so
    # clients-off programs carry zero extra arrays and stay
    # byte-identical to pre-r09 builds, the pv_* mailbox trick).
    # `session_seq[sid]` is the highest client seq APPLIED for that
    # pre-registered sid (-1 = none): pure state-machine state, rebuilt
    # like `digest` — live table tracks the applied prefix, snapshot
    # table is the durable copy compaction writes and restart /
    # InstallSnapshot rewind to.
    session_seq: jnp.ndarray | None = None       # i32[S], live table
    snap_session_seq: jnp.ndarray | None = None  # i32[S], snapshot table


class Mailbox(NamedTuple):
    """One slot per (dst, src, rpc-type); fields mirror core/rpc.py.

    Leading dims `[G, K_dst, K_src]` as the in-flight buffer — receiver-
    major, so the per-node vmap slices each node's per-sender inbox with
    no transpose (see sim/step.py `tick`). `*_present` is the occupancy
    bit; all other fields are only meaningful under it.
    """

    rv_req_present: jnp.ndarray   # bool
    rv_req_term: jnp.ndarray      # i32
    rv_req_lli: jnp.ndarray       # i32 — last_log_index
    rv_req_llt: jnp.ndarray       # i32 — last_log_term

    rv_resp_present: jnp.ndarray  # bool
    rv_resp_term: jnp.ndarray     # i32
    rv_resp_granted: jnp.ndarray  # bool

    # AppendEntries carries NO entry payloads on the batched path: the
    # receiver pulls the n entries straight out of the sender's ring
    # (sim/step.py `_on_ae_req`), which is bit-exact because the covered
    # range (prev, prev+n] cannot change between the send (phase T of
    # tick t) and the delivery (phase D of t+1 reads end-of-t state):
    # phase C appends strictly above it, phase A never writes the ring,
    # and ring-slot collisions with new appends would need an index gap
    # of L, impossible inside one bounded window. This deletes the
    # send-side gather (the single hottest op group, DESIGN.md §7) and
    # two [G, K, K, E] arrays from the scan carry.
    ae_req_present: jnp.ndarray   # bool
    ae_req_term: jnp.ndarray      # i32
    ae_req_prev_index: jnp.ndarray  # i32
    ae_req_prev_term: jnp.ndarray   # i32
    ae_req_n: jnp.ndarray         # i32 — number of valid entries
    ae_req_commit: jnp.ndarray    # i32 — leader_commit

    ae_resp_present: jnp.ndarray  # bool
    ae_resp_term: jnp.ndarray     # i32
    ae_resp_success: jnp.ndarray  # bool
    ae_resp_match: jnp.ndarray    # i32

    is_req_present: jnp.ndarray   # bool
    is_req_term: jnp.ndarray      # i32
    is_req_snap_index: jnp.ndarray   # i32
    is_req_snap_term: jnp.ndarray    # i32
    is_req_snap_digest: jnp.ndarray  # u32
    is_req_snap_voters: jnp.ndarray  # i32

    is_resp_present: jnp.ndarray  # bool
    is_resp_term: jnp.ndarray     # i32
    is_resp_match: jnp.ndarray    # i32

    # PreVote slots — present only when `cfg.prevote` (None otherwise:
    # a None NamedTuple field is an empty pytree subtree, so the
    # prevote-off program carries zero extra arrays and stays
    # byte-identical to builds that predate the feature).
    pv_req_present: jnp.ndarray | None = None   # bool
    pv_req_term: jnp.ndarray | None = None      # i32 — PROPOSED term
    pv_req_lli: jnp.ndarray | None = None       # i32
    pv_req_llt: jnp.ndarray | None = None       # i32
    pv_resp_present: jnp.ndarray | None = None  # bool
    pv_resp_term: jnp.ndarray | None = None     # i32 — responder's term
    pv_resp_req_term: jnp.ndarray | None = None  # i32 — echoed proposal
    pv_resp_granted: jnp.ndarray | None = None  # bool

    # TimeoutNow (leadership transfer, DESIGN.md §2d) — present only
    # when the transfer schedule is statically on.
    tn_present: jnp.ndarray | None = None       # bool
    tn_term: jnp.ndarray | None = None          # i32

    # InstallSnapshot's session-table payload (DESIGN.md §10) — the
    # snapshot dedup table rides the message BY VALUE like the other
    # snap_* fields (the sender may compact between send and delivery,
    # so a receiver-pull of its CURRENT snapshot table would diverge
    # from the oracle). Present only with scheduled clients on;
    # meaningful under is_req_present.
    is_req_snap_sessions: jnp.ndarray | None = None  # i32[..., S]


class State(NamedTuple):
    nodes: PerNode        # leaves [G, K, ...]
    mailbox: Mailbox      # in-flight: sent last tick, delivered this tick
    alive_prev: jnp.ndarray  # bool[G, K] — liveness during the previous tick
    group_id: jnp.ndarray    # i32[G] — GLOBAL group index. Carried in state
    # (not derived from array positions) so that a device shard of the G
    # axis keeps simulating its own groups' seed streams: inside shard_map
    # an arange over the local shape would alias every shard onto groups
    # [0, G_local), silently duplicating universes.
    #
    # Open-loop client-side state (clients/state.py, [G, S] leaves) —
    # present only when the scheduled client traffic is on (None = an
    # empty subtree, keeping clients-off pytrees identical to pre-r09).
    # Environment state like the fault schedules, NOT replicated state:
    # the tick consumes its submit pulses in phase C and the post-tick
    # client transition (clients/workload.py) rewrites it.
    clients: "ClientState | None" = None


def empty_mailbox(lead_shape: tuple, prevote: bool = False,
                  transfer: bool = False, client_slots: int = 0) -> Mailbox:
    """Zero mailbox with the given leading shape: `(g, k, k)` for the
    in-flight buffer ([G, dst, src]), `(k,)` for a per-node outbox inside
    the vmapped step. PreVote / TimeoutNow / session-table slots are
    materialized only when their schedules are on."""
    def z(dtype, *extra):
        return jnp.zeros(tuple(lead_shape) + extra, dtype)

    pv = {}
    if prevote:
        pv = dict(pv_req_present=z(BOOL), pv_req_term=z(I32),
                  pv_req_lli=z(I32), pv_req_llt=z(I32),
                  pv_resp_present=z(BOOL), pv_resp_term=z(I32),
                  pv_resp_req_term=z(I32), pv_resp_granted=z(BOOL))
    if transfer:
        pv.update(tn_present=z(BOOL), tn_term=z(I32))
    if client_slots:
        pv["is_req_snap_sessions"] = z(I32, client_slots)
    return Mailbox(
        rv_req_present=z(BOOL), rv_req_term=z(I32), rv_req_lli=z(I32),
        rv_req_llt=z(I32),
        rv_resp_present=z(BOOL), rv_resp_term=z(I32), rv_resp_granted=z(BOOL),
        ae_req_present=z(BOOL), ae_req_term=z(I32), ae_req_prev_index=z(I32),
        ae_req_prev_term=z(I32), ae_req_n=z(I32), ae_req_commit=z(I32),
        ae_resp_present=z(BOOL), ae_resp_term=z(I32), ae_resp_success=z(BOOL),
        ae_resp_match=z(I32),
        is_req_present=z(BOOL), is_req_term=z(I32), is_req_snap_index=z(I32),
        is_req_snap_term=z(I32), is_req_snap_digest=z(U32),
        is_req_snap_voters=z(I32),
        is_resp_present=z(BOOL), is_resp_term=z(I32), is_resp_match=z(I32),
        **pv,
    )


def init(cfg: RaftConfig, n_groups: int | None = None) -> State:
    """Fresh state bit-matching `Node.__init__` (node.py:28-57) per node."""
    g = cfg.n_groups if n_groups is None else n_groups
    k, cap = cfg.k, cfg.log_cap

    g_idx = jnp.arange(g, dtype=I32)[:, None]          # [G, 1]
    i_idx = jnp.arange(k, dtype=I32)[None, :]          # [1, K]
    # __init__ runs _reset_election_timer once: deadline = draw 0, draws = 1.
    deadline = jrng.election_deadline(cfg.seed, g_idx, i_idx, 0,
                                      cfg.election_min, cfg.election_range)
    if cfg.nem_skew:
        # The initial draw happens "at" tick 0 on every engine — a
        # nemesis clock-skew span covering tick 0 skews it (DESIGN.md
        # §14), exactly like Node.__init__'s reset with now == 0.
        deadline = jnp.maximum(1, deadline + jrng.nem_deadline_extra(
            cfg.seed, cfg.nem_skew, g_idx, i_idx, 0))
    deadline = jnp.broadcast_to(deadline, (g, k))

    def z(dtype, *extra):
        return jnp.zeros((g, k) + extra, dtype)

    sess = {}
    if cfg.clients_u32:
        # Slots 0..S-1 are born registered with no applied commands
        # (table value -1) — bit-matching Node.__init__'s pre-registered
        # snap_sessions under the same config.
        sess = dict(
            session_seq=jnp.full((g, k, cfg.client_slots), -1, I32),
            snap_session_seq=jnp.full((g, k, cfg.client_slots), -1, I32))
    nodes = PerNode(
        term=z(I32),
        voted_for=jnp.full((g, k), NO_VOTE, I32),
        snap_index=z(I32), snap_term=z(I32), snap_digest=z(U32),
        snap_voters=jnp.full((g, k), cfg.full_mask, I32),
        rng_draws=jnp.ones((g, k), I32),
        last_index=z(I32),
        log_term=z(I32, cap), log_payload=z(I32, cap),
        role=jnp.full((g, k), FOLLOWER, I32),
        leader_id=jnp.full((g, k), NO_VOTE, I32),
        commit=z(I32), applied=z(I32), digest=z(U32),
        votes=z(BOOL, k),
        next_index=jnp.ones((g, k, k), I32),
        match_index=z(I32, k),
        election_elapsed=z(I32), heartbeat_elapsed=z(I32),
        deadline=deadline,
        leader_elapsed=z(I32),
        ack_time=jnp.full((g, k, k), -1, I32),
        sched_read_index=jnp.full((g, k), -1, I32),
        sched_read_reg=z(I32),
        reads_done=z(I32),
        **sess,
    )
    clients = None
    if cfg.clients_u32:
        from raft_tpu.clients.state import clients_init
        clients = clients_init(cfg, g)
    st = State(
        nodes=nodes,
        mailbox=empty_mailbox((g, k, k), cfg.prevote,
                              cfg.transfer_u32 != 0,
                              cfg.client_slots if cfg.clients_u32 else 0),
        alive_prev=jnp.ones((g, k), BOOL),
        group_id=jnp.arange(g, dtype=I32),
        clients=clients,
    )
    # The RESIDENT form is the narrow one when any narrow dial is on
    # (DESIGN.md §18): initial values are all in range, so this first
    # narrowing can never latch.
    return narrow_state(cfg, st)


# --------------------------------------------------------------------------
# Narrow-native resident layout (r19, DESIGN.md §18).
#
# The dtype map below is THE contract: which State leaves the
# `narrow_*` dials re-declare at narrow native dtypes, keyed by the
# leaf's checkpoint dot-path name (sim/checkpoint.iter_named_leaves).
# Every other subsystem derives from it — the tick boundary
# (sim/step.py widens on entry / narrows on exit), the kernel seam
# (pkernel._to_kstate widens, kfinish re-narrows), checkpoint.load's
# by-name narrow/widen hop, the bytemodel's narrow resident
# accounting, and the contract auditor's narrowing pass.
#
# Range proofs (why each narrow dtype is sufficient — the full table
# with per-leaf bounds lives in DESIGN.md §18):
#   u16  terms / log indices / tick clocks: bounded by the run's term
#        and index envelope; exceeding 65535 latches (below).
#   i8   role (0..2), voted_for / leader_id (-1..k-1, kernel k <= 30),
#        ae_req_n (0..E), client inflight/submit (0/1).
#   i16  -1-sentinel lanes (ack_time, sched_read_index, session
#        tables — session seqs are 10-bit by construction,
#        config.SESSION_SEQ_MASK; last_lat ack latencies).
# Deliberately kept wide: snap_digest / digest / is_req_snap_digest
# (u32 hash chains), log_payload (full 30-bit command space),
# group_id (i32 — it carries the overflow latch in bit 31 and feeds
# the u32 seed hashes), and the Flight recorder rings (parity
# machinery, not hot resident state).
#
# Overflow latch (the PR 13 sticky-bit idiom, pkernel._ring_base_ov):
# a value that does not survive the narrow round-trip ORs bit 31 of
# the group's `group_id` lane — sticky, because the tick never writes
# group_id and `narrow_state` re-ORs it — and every host boundary
# (checkpoint.save / kfinish / the run drivers) refuses a latched
# state with a loud ValueError. Never silent corruption.

_NARROW_LATCH = jnp.int32(-(2 ** 31))     # bit 31 of the i32 group_id

# PerNode scalar lanes at u16 under narrow_scalars (nonnegative by
# construction: terms, absolute log indices, monotone counters, clock
# values — see DESIGN.md §18 for the per-leaf bound).
_NODE_U16 = ("term", "snap_index", "snap_term", "rng_draws",
             "last_index", "commit", "applied", "next_index",
             "match_index", "election_elapsed", "heartbeat_elapsed",
             "deadline", "leader_elapsed", "sched_read_reg",
             "reads_done")
# Mailbox term/index payload lanes at u16 under narrow_mailbox
# (meaningful only under their presence bits; always nonnegative).
_MB_U16 = ("rv_req_term", "rv_req_lli", "rv_req_llt", "rv_resp_term",
           "ae_req_term", "ae_req_prev_index", "ae_req_prev_term",
           "ae_req_commit", "ae_resp_term", "ae_resp_match",
           "is_req_term", "is_req_snap_index", "is_req_snap_term",
           "is_resp_term", "is_resp_match")
# PreVote / TimeoutNow mailbox slots exist only under their schedules —
# listed apart so narrow_spec maps exactly the leaves the cfg carries
# (the byte-model audit flags any spec entry with no matching leaf).
_MB_PV_U16 = ("pv_req_term", "pv_req_lli", "pv_req_llt", "pv_resp_term",
              "pv_resp_req_term")
# ClientState lanes under narrow_clients live with their NamedTuple:
# clients.state.NARROW_CLIENT_SPEC (tick stamps / op counters at u16,
# 0/1 flags at i8, -1-sentinel latency at i16).


def narrow_spec(cfg: RaftConfig) -> dict:
    """name -> narrow jnp dtype for every State leaf the cfg's narrow
    dials re-declare (checkpoint dot-path names). Empty dict when all
    narrow dials are off — THE gate every boundary helper below keys
    on. `snap_voters` bitmasks narrow only when they fit 16 lanes."""
    spec: dict = {}
    if cfg.narrow_scalars:
        for n in _NODE_U16:
            spec[f"nodes.{n}"] = U16
        for n in ("voted_for", "role", "leader_id"):
            spec[f"nodes.{n}"] = I8
        spec["nodes.ack_time"] = I16
        spec["nodes.sched_read_index"] = I16
        if cfg.k <= 16:
            spec["nodes.snap_voters"] = U16
    if cfg.narrow_ring:
        spec["nodes.log_term"] = U16
    if cfg.narrow_mailbox:
        for n in _MB_U16:
            spec[f"mailbox.{n}"] = U16
        if cfg.prevote:
            for n in _MB_PV_U16:
                spec[f"mailbox.{n}"] = U16
        if cfg.transfer_u32:
            spec["mailbox.tn_term"] = U16
        spec["mailbox.ae_req_n"] = I8
        if cfg.k <= 16:
            spec["mailbox.is_req_snap_voters"] = U16
    if cfg.narrow_clients and cfg.clients_u32:
        from raft_tpu.clients.state import (NARROW_CLIENT_SPEC,
                                            active_client_leaves)
        spec["nodes.session_seq"] = I16
        spec["nodes.snap_session_seq"] = I16
        spec["mailbox.is_req_snap_sessions"] = I16
        # Iterate the cfg's ACTIVE leaves: the admission-gated shed
        # lane must not map a spec entry with no matching leaf.
        for n in active_client_leaves(cfg):
            spec[f"clients.{n}"] = NARROW_CLIENT_SPEC[n]
    return spec


def full_narrow_spec(cfg: RaftConfig) -> dict:
    """The spec with every narrow dial forced on — the set of (name,
    dtype) hops checkpoint.load accepts regardless of which dials the
    writing run had (a dtype outside this map is a semantic mismatch
    and still refuses)."""
    return narrow_spec(dataclasses.replace(
        cfg, narrow_scalars=True, narrow_ring=True, narrow_mailbox=True,
        narrow_clients=True))


def narrow_active(cfg: RaftConfig) -> bool:
    """True iff the resident State form differs from the wide one (a
    lone `narrow_clients` dial on a clients-off universe maps zero
    leaves, so it is NOT active — the spec, not the flags, decides)."""
    return bool(narrow_spec(cfg))


def _map_named(tree, prefix, fn):
    """Rebuild a NamedTuple pytree applying fn(dot_path, leaf) to every
    non-None leaf — the iter_named_leaves naming rule, reconstructing."""
    if tree is None:
        return None
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        return type(tree)(*(_map_named(getattr(tree, f), f"{prefix}{f}.",
                                       fn) for f in tree._fields))
    return fn(prefix[:-1], tree)


def narrow_state(cfg: RaftConfig, st: State) -> State:
    """Wide State -> the cfg's narrow resident form, latching bit 31 of
    `group_id` for any group holding a value that does not survive the
    round-trip (sticky: an already-latched group stays latched because
    the unlatched lanes pass through `where` unchanged). Identity when
    every narrow dial is off. Traceable — runs inside the jitted tick
    boundary every tick."""
    spec = narrow_spec(cfg)
    if not spec:
        return st
    overflow = []

    def leaf(name, a):
        dt = spec.get(name)
        if dt is None or a.dtype == dt:
            return a
        na = a.astype(dt)
        bad = (na.astype(a.dtype) != a).reshape(a.shape[0], -1)
        overflow.append(jnp.any(bad, axis=1))
        return na

    out = _map_named(st, "", leaf)
    if not overflow:
        return out
    ov = overflow[0]
    for b in overflow[1:]:
        ov = ov | b
    return out._replace(group_id=jnp.where(
        ov, out.group_id | _NARROW_LATCH, out.group_id))


def widen_state(cfg: RaftConfig, st: State) -> State:
    """Narrow resident form -> the audited wide compute form (every
    narrowed lane back at i32; zero-extend for the unsigned lanes,
    sign-extend for the -1-sentinel ones). group_id passes through
    unchanged — the latch must survive the round-trip. Identity when
    every narrow dial is off."""
    spec = narrow_spec(cfg)
    if not spec:
        return st

    def leaf(name, a):
        if name in spec and a.dtype != I32:
            return a.astype(I32)
        return a

    return _map_named(st, "", leaf)


def narrow_overflow(st: State) -> jnp.ndarray:
    """bool[G]: groups whose narrow-dtype latch has fired."""
    return st.group_id < 0


def check_narrow_overflow(cfg: RaftConfig, st: State) -> None:
    """The host-boundary refusal (checkpoint.save, pkernel.kfinish, the
    run drivers): raise ValueError naming the latched groups — a term/
    index/clock outgrew its narrow dtype, so every later value in those
    groups is suspect. Mirrors pkernel._check_ring_overflow."""
    if not narrow_active(cfg):
        return
    import numpy as np
    ov = np.asarray(narrow_overflow(st))
    if ov.any():
        bad = np.nonzero(ov)[0]
        raise ValueError(
            f"narrow-dtype overflow latched in {len(bad)} group(s) "
            f"(first: {bad[:8].tolist()}): a value outgrew its narrow "
            f"native dtype (DESIGN.md §18 range table). Re-run with the "
            f"narrow_* dials off — results after the latch tick are "
            f"invalid and are refused rather than silently truncated")
