"""Struct-of-arrays state for the batched TPU path (DESIGN.md §5).

Every field of the CPU oracle's `Node` (core/node.py) becomes an array with
leading dims `[G, K]` (G = independent Raft groups, K = replicas per
group). Logs are **ring-addressed by absolute index**: the entry at
absolute index ``i`` lives in slot ``(i - 1) % L``. Because the window
invariant ``last_index - snap_index <= L`` holds (DESIGN.md §3), the
mapping is injective over the live window — so compaction and
InstallSnapshot's keep-the-suffix case move ``snap_index`` without any
data movement, and truncation is just lowering ``last_index``.

The in-memory `Transport` (core/transport.py) becomes the dense `Mailbox`:
one slot per (group, src, dst, message-type), exploiting the tick
contract's guarantee of at most one message per (type, src, dst) per tick
(DESIGN.md §2). `Mailbox` triples as the in-flight buffer (`[G, K, K]`
leading dims), a node's inbox (`[K_src]` after transpose + vmap), and a
node's outbox (`[K_dst]` inside the per-node step).

The observability layer (DESIGN.md §8) treats this State as its whole
read surface: the per-tick safety fold (sim/check.py `tick_safety`) and
the flight recorder's message-volume signal (obs/recorder.py, summing
the `*_present` occupancy bits below) are pure functions of a post-tick
State — adding a leaf here extends the triage/diff surface
automatically (utils/trees names leaves by pytree path).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from raft_tpu.config import RaftConfig
from raft_tpu.core.node import FOLLOWER, NO_VOTE
from raft_tpu.utils import jrng

I32 = jnp.int32
U32 = jnp.uint32
BOOL = jnp.bool_


class PerNode(NamedTuple):
    """Per-replica state; one leaf per `Node` attribute (core/node.py).

    Leading dims `[G, K]` in a full `State`; scalars / `[K]` / `[L]`
    inside the vmapped per-node step.
    """

    # Durable (survives crash/restart — node.py:36-43).
    term: jnp.ndarray         # i32
    voted_for: jnp.ndarray    # i32, NO_VOTE = -1
    snap_index: jnp.ndarray   # i32
    snap_term: jnp.ndarray    # i32
    snap_digest: jnp.ndarray  # u32
    snap_voters: jnp.ndarray  # i32 — voter bitmask as of the snapshot prefix
    rng_draws: jnp.ndarray    # i32 — monotone deadline-draw counter
    last_index: jnp.ndarray   # i32 (CPU: derived from len(log); explicit here)
    log_term: jnp.ndarray     # i32[L], ring slot (i-1) % L
    log_payload: jnp.ndarray  # i32[L]
    # Volatile (reset on restart — node.py:45-57).
    role: jnp.ndarray         # i32: FOLLOWER/CANDIDATE/LEADER
    leader_id: jnp.ndarray    # i32
    commit: jnp.ndarray       # i32
    applied: jnp.ndarray      # i32
    digest: jnp.ndarray       # u32 — state-machine hash chain
    votes: jnp.ndarray        # bool[K]
    next_index: jnp.ndarray   # i32[K]
    match_index: jnp.ndarray  # i32[K]
    election_elapsed: jnp.ndarray   # i32
    heartbeat_elapsed: jnp.ndarray  # i32
    deadline: jnp.ndarray     # i32
    leader_elapsed: jnp.ndarray     # i32 — PreVote lease clock (node.py)
    # Scheduled-read state (DESIGN.md §2c; node.py `sched_read` /
    # `ack_time` / `reads_done`). Always present for a stable trace
    # surface; all writes are statically gated on `cfg.read_every`.
    ack_time: jnp.ndarray           # i32[K] — last current-term resp tick
    sched_read_index: jnp.ndarray   # i32 — read point, -1 = none
    sched_read_reg: jnp.ndarray     # i32 — registration tick
    reads_done: jnp.ndarray         # i32 — completed linearizable reads
    # Exactly-once session dedup tables (DESIGN.md §10; node.py
    # `sessions` / `snap_sessions`) — present only when the scheduled
    # client traffic is on (cfg.clients_u32; None otherwise, so
    # clients-off programs carry zero extra arrays and stay
    # byte-identical to pre-r09 builds, the pv_* mailbox trick).
    # `session_seq[sid]` is the highest client seq APPLIED for that
    # pre-registered sid (-1 = none): pure state-machine state, rebuilt
    # like `digest` — live table tracks the applied prefix, snapshot
    # table is the durable copy compaction writes and restart /
    # InstallSnapshot rewind to.
    session_seq: jnp.ndarray | None = None       # i32[S], live table
    snap_session_seq: jnp.ndarray | None = None  # i32[S], snapshot table


class Mailbox(NamedTuple):
    """One slot per (dst, src, rpc-type); fields mirror core/rpc.py.

    Leading dims `[G, K_dst, K_src]` as the in-flight buffer — receiver-
    major, so the per-node vmap slices each node's per-sender inbox with
    no transpose (see sim/step.py `tick`). `*_present` is the occupancy
    bit; all other fields are only meaningful under it.
    """

    rv_req_present: jnp.ndarray   # bool
    rv_req_term: jnp.ndarray      # i32
    rv_req_lli: jnp.ndarray       # i32 — last_log_index
    rv_req_llt: jnp.ndarray       # i32 — last_log_term

    rv_resp_present: jnp.ndarray  # bool
    rv_resp_term: jnp.ndarray     # i32
    rv_resp_granted: jnp.ndarray  # bool

    # AppendEntries carries NO entry payloads on the batched path: the
    # receiver pulls the n entries straight out of the sender's ring
    # (sim/step.py `_on_ae_req`), which is bit-exact because the covered
    # range (prev, prev+n] cannot change between the send (phase T of
    # tick t) and the delivery (phase D of t+1 reads end-of-t state):
    # phase C appends strictly above it, phase A never writes the ring,
    # and ring-slot collisions with new appends would need an index gap
    # of L, impossible inside one bounded window. This deletes the
    # send-side gather (the single hottest op group, DESIGN.md §7) and
    # two [G, K, K, E] arrays from the scan carry.
    ae_req_present: jnp.ndarray   # bool
    ae_req_term: jnp.ndarray      # i32
    ae_req_prev_index: jnp.ndarray  # i32
    ae_req_prev_term: jnp.ndarray   # i32
    ae_req_n: jnp.ndarray         # i32 — number of valid entries
    ae_req_commit: jnp.ndarray    # i32 — leader_commit

    ae_resp_present: jnp.ndarray  # bool
    ae_resp_term: jnp.ndarray     # i32
    ae_resp_success: jnp.ndarray  # bool
    ae_resp_match: jnp.ndarray    # i32

    is_req_present: jnp.ndarray   # bool
    is_req_term: jnp.ndarray      # i32
    is_req_snap_index: jnp.ndarray   # i32
    is_req_snap_term: jnp.ndarray    # i32
    is_req_snap_digest: jnp.ndarray  # u32
    is_req_snap_voters: jnp.ndarray  # i32

    is_resp_present: jnp.ndarray  # bool
    is_resp_term: jnp.ndarray     # i32
    is_resp_match: jnp.ndarray    # i32

    # PreVote slots — present only when `cfg.prevote` (None otherwise:
    # a None NamedTuple field is an empty pytree subtree, so the
    # prevote-off program carries zero extra arrays and stays
    # byte-identical to builds that predate the feature).
    pv_req_present: jnp.ndarray | None = None   # bool
    pv_req_term: jnp.ndarray | None = None      # i32 — PROPOSED term
    pv_req_lli: jnp.ndarray | None = None       # i32
    pv_req_llt: jnp.ndarray | None = None       # i32
    pv_resp_present: jnp.ndarray | None = None  # bool
    pv_resp_term: jnp.ndarray | None = None     # i32 — responder's term
    pv_resp_req_term: jnp.ndarray | None = None  # i32 — echoed proposal
    pv_resp_granted: jnp.ndarray | None = None  # bool

    # TimeoutNow (leadership transfer, DESIGN.md §2d) — present only
    # when the transfer schedule is statically on.
    tn_present: jnp.ndarray | None = None       # bool
    tn_term: jnp.ndarray | None = None          # i32

    # InstallSnapshot's session-table payload (DESIGN.md §10) — the
    # snapshot dedup table rides the message BY VALUE like the other
    # snap_* fields (the sender may compact between send and delivery,
    # so a receiver-pull of its CURRENT snapshot table would diverge
    # from the oracle). Present only with scheduled clients on;
    # meaningful under is_req_present.
    is_req_snap_sessions: jnp.ndarray | None = None  # i32[..., S]


class State(NamedTuple):
    nodes: PerNode        # leaves [G, K, ...]
    mailbox: Mailbox      # in-flight: sent last tick, delivered this tick
    alive_prev: jnp.ndarray  # bool[G, K] — liveness during the previous tick
    group_id: jnp.ndarray    # i32[G] — GLOBAL group index. Carried in state
    # (not derived from array positions) so that a device shard of the G
    # axis keeps simulating its own groups' seed streams: inside shard_map
    # an arange over the local shape would alias every shard onto groups
    # [0, G_local), silently duplicating universes.
    #
    # Open-loop client-side state (clients/state.py, [G, S] leaves) —
    # present only when the scheduled client traffic is on (None = an
    # empty subtree, keeping clients-off pytrees identical to pre-r09).
    # Environment state like the fault schedules, NOT replicated state:
    # the tick consumes its submit pulses in phase C and the post-tick
    # client transition (clients/workload.py) rewrites it.
    clients: "ClientState | None" = None


def empty_mailbox(lead_shape: tuple, prevote: bool = False,
                  transfer: bool = False, client_slots: int = 0) -> Mailbox:
    """Zero mailbox with the given leading shape: `(g, k, k)` for the
    in-flight buffer ([G, dst, src]), `(k,)` for a per-node outbox inside
    the vmapped step. PreVote / TimeoutNow / session-table slots are
    materialized only when their schedules are on."""
    def z(dtype, *extra):
        return jnp.zeros(tuple(lead_shape) + extra, dtype)

    pv = {}
    if prevote:
        pv = dict(pv_req_present=z(BOOL), pv_req_term=z(I32),
                  pv_req_lli=z(I32), pv_req_llt=z(I32),
                  pv_resp_present=z(BOOL), pv_resp_term=z(I32),
                  pv_resp_req_term=z(I32), pv_resp_granted=z(BOOL))
    if transfer:
        pv.update(tn_present=z(BOOL), tn_term=z(I32))
    if client_slots:
        pv["is_req_snap_sessions"] = z(I32, client_slots)
    return Mailbox(
        rv_req_present=z(BOOL), rv_req_term=z(I32), rv_req_lli=z(I32),
        rv_req_llt=z(I32),
        rv_resp_present=z(BOOL), rv_resp_term=z(I32), rv_resp_granted=z(BOOL),
        ae_req_present=z(BOOL), ae_req_term=z(I32), ae_req_prev_index=z(I32),
        ae_req_prev_term=z(I32), ae_req_n=z(I32), ae_req_commit=z(I32),
        ae_resp_present=z(BOOL), ae_resp_term=z(I32), ae_resp_success=z(BOOL),
        ae_resp_match=z(I32),
        is_req_present=z(BOOL), is_req_term=z(I32), is_req_snap_index=z(I32),
        is_req_snap_term=z(I32), is_req_snap_digest=z(U32),
        is_req_snap_voters=z(I32),
        is_resp_present=z(BOOL), is_resp_term=z(I32), is_resp_match=z(I32),
        **pv,
    )


def init(cfg: RaftConfig, n_groups: int | None = None) -> State:
    """Fresh state bit-matching `Node.__init__` (node.py:28-57) per node."""
    g = cfg.n_groups if n_groups is None else n_groups
    k, cap = cfg.k, cfg.log_cap

    g_idx = jnp.arange(g, dtype=I32)[:, None]          # [G, 1]
    i_idx = jnp.arange(k, dtype=I32)[None, :]          # [1, K]
    # __init__ runs _reset_election_timer once: deadline = draw 0, draws = 1.
    deadline = jrng.election_deadline(cfg.seed, g_idx, i_idx, 0,
                                      cfg.election_min, cfg.election_range)
    if cfg.nem_skew:
        # The initial draw happens "at" tick 0 on every engine — a
        # nemesis clock-skew span covering tick 0 skews it (DESIGN.md
        # §14), exactly like Node.__init__'s reset with now == 0.
        deadline = jnp.maximum(1, deadline + jrng.nem_deadline_extra(
            cfg.seed, cfg.nem_skew, g_idx, i_idx, 0))
    deadline = jnp.broadcast_to(deadline, (g, k))

    def z(dtype, *extra):
        return jnp.zeros((g, k) + extra, dtype)

    sess = {}
    if cfg.clients_u32:
        # Slots 0..S-1 are born registered with no applied commands
        # (table value -1) — bit-matching Node.__init__'s pre-registered
        # snap_sessions under the same config.
        sess = dict(
            session_seq=jnp.full((g, k, cfg.client_slots), -1, I32),
            snap_session_seq=jnp.full((g, k, cfg.client_slots), -1, I32))
    nodes = PerNode(
        term=z(I32),
        voted_for=jnp.full((g, k), NO_VOTE, I32),
        snap_index=z(I32), snap_term=z(I32), snap_digest=z(U32),
        snap_voters=jnp.full((g, k), cfg.full_mask, I32),
        rng_draws=jnp.ones((g, k), I32),
        last_index=z(I32),
        log_term=z(I32, cap), log_payload=z(I32, cap),
        role=jnp.full((g, k), FOLLOWER, I32),
        leader_id=jnp.full((g, k), NO_VOTE, I32),
        commit=z(I32), applied=z(I32), digest=z(U32),
        votes=z(BOOL, k),
        next_index=jnp.ones((g, k, k), I32),
        match_index=z(I32, k),
        election_elapsed=z(I32), heartbeat_elapsed=z(I32),
        deadline=deadline,
        leader_elapsed=z(I32),
        ack_time=jnp.full((g, k, k), -1, I32),
        sched_read_index=jnp.full((g, k), -1, I32),
        sched_read_reg=z(I32),
        reads_done=z(I32),
        **sess,
    )
    clients = None
    if cfg.clients_u32:
        from raft_tpu.clients.state import clients_init
        clients = clients_init(cfg, g)
    return State(
        nodes=nodes,
        mailbox=empty_mailbox((g, k, k), cfg.prevote,
                              cfg.transfer_u32 != 0,
                              cfg.client_slots if cfg.clients_u32 else 0),
        alive_prev=jnp.ones((g, k), BOOL),
        group_id=jnp.arange(g, dtype=I32),
        clients=clients,
    )
