"""The TPU batched backend: pure step over struct-of-arrays Raft state.

`state.py` defines the `[G, K]` SoA pytree (DESIGN.md §5); `step.py` is the
pure tick function mirroring `core/node.py` branch-for-branch; `run.py`
wraps it in `lax.scan` under `jit` and accumulates metrics.
"""

from raft_tpu.sim.state import Mailbox, PerNode, State, init
from raft_tpu.sim.step import tick
from raft_tpu.sim.run import run, Metrics

__all__ = ["Mailbox", "PerNode", "State", "init", "tick", "run", "Metrics"]
