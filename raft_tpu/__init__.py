"""raft_tpu — a TPU-native massively-batched Raft consensus framework.

Two backends behind one deterministic tick contract (see DESIGN.md):

- ``raft_tpu.core``: the CPU reference path — classical ``Node`` /
  ``Transport`` / ``Cluster`` objects, one group at a time. Ground truth.
- ``raft_tpu.sim``: the TPU batched path — a pure ``tick`` function over a
  struct-of-arrays state for ``[n_groups, k]`` replicas, vmapped/jitted/
  scanned (``sim.step``, ``sim.run``), sharded over a device mesh
  (``raft_tpu.parallel``), with quorum reductions in ``raft_tpu.ops``.
  ``tests/test_differential.py`` holds the two backends bit-identical
  per node per tick under every fault class.

Reference parity note: the upstream reference (qzwsq/raft, expected at
/root/reference) was empty at survey and build time — see SURVEY.md. The
behavior contract implemented here is the driver-confirmed north star in
BASELINE.json plus the canonical Raft specification.
"""

from raft_tpu.config import RaftConfig

__all__ = ["RaftConfig"]
__version__ = "0.1.0"
