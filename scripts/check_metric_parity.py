"""Static metric-surface parity check: the XLA path's `Metrics`, the
kernel's `KMetrics`, its wire order `METRIC_LEAVES`, and the flight
recorder's `Flight`/`FLIGHT_LEAVES` must stay name-, dtype-, order-,
and shape-aligned — the bench promotion gates and kfinish's name-based
wire indexing all assume it. Exits nonzero on any drift; runs in tier-1
via tests/test_obs.py (fast: builds two host-side pytrees, no jit).

    python scripts/check_metric_parity.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

# Static check — never let the import initialize a real accelerator.
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def check() -> list[str]:
    """Returns the list of parity problems (empty = aligned)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from raft_tpu.clients.state import CLIENT_LEAVES, ClientState, \
        clients_init
    from raft_tpu.config import RaftConfig
    from raft_tpu.obs.recorder import FLIGHT_LEAVES, RING, Flight, flight_init
    from raft_tpu.sim.pkernel import (CLIENT_METRIC_LEAVES, KMetrics,
                                      METRIC_LEAVES, N_METRIC_LEAVES,
                                      _active_metric_leaves)
    from raft_tpu.sim.run import HIST_SIZE, Metrics, metrics_init

    problems = []
    if KMetrics._fields != METRIC_LEAVES:
        problems.append(f"KMetrics fields {KMetrics._fields} != wire order "
                        f"METRIC_LEAVES {METRIC_LEAVES}")
    if set(Metrics._fields) != set(METRIC_LEAVES):
        problems.append(f"Metrics fields {sorted(Metrics._fields)} != "
                        f"METRIC_LEAVES names {sorted(METRIC_LEAVES)}")
    if N_METRIC_LEAVES != len(METRIC_LEAVES):
        problems.append("N_METRIC_LEAVES out of sync with METRIC_LEAVES")
    if Flight._fields != FLIGHT_LEAVES:
        problems.append(f"Flight fields {Flight._fields} != wire order "
                        f"FLIGHT_LEAVES {FLIGHT_LEAVES}")
    if ClientState._fields != CLIENT_LEAVES:
        problems.append(f"ClientState fields {ClientState._fields} != wire "
                        f"order CLIENT_LEAVES {CLIENT_LEAVES}")

    # The active wire subset must drop EXACTLY the client lanes when
    # clients are off, and be the full tuple when on.
    cfg_off = RaftConfig(seed=1)
    cfg_on = RaftConfig(seed=1, sessions=True, cmds_per_tick=0,
                        client_rate=0.2, client_slots=3)
    if _active_metric_leaves(cfg_on) != METRIC_LEAVES:
        problems.append("clients-on active metric leaves != METRIC_LEAVES")
    want_off = tuple(n for n in METRIC_LEAVES
                     if n not in CLIENT_METRIC_LEAVES)
    if _active_metric_leaves(cfg_off) != want_off:
        problems.append(f"clients-off active metric leaves "
                        f"{_active_metric_leaves(cfg_off)} != {want_off}")

    g = 4
    # The kernel wire is i32 lanes: every metric leaf must be i32, with
    # the shapes kinit folds ([G] per-group, scalar, or [H] histogram);
    # client lanes None with clients off, concrete with clients on.
    want_shape = {"committed": (g,), "leaderless": (g,), "elections": (),
                  "hist": (HIST_SIZE,), "max_latency": (), "safety": (g,),
                  "client_acked": (g,), "client_retries": (g,),
                  "client_hist": (HIST_SIZE,), "client_max_lat": ()}
    for clients in (False, True):
        m = metrics_init(g, clients=clients)
        for name in Metrics._fields:
            leaf = getattr(m, name)
            if leaf is None:
                if clients or name not in CLIENT_METRIC_LEAVES:
                    problems.append(f"Metrics.{name} unexpectedly None "
                                    f"(clients={clients})")
                continue
            if not clients and name in CLIENT_METRIC_LEAVES:
                problems.append(f"Metrics.{name} present with clients off")
            if leaf.dtype != jnp.int32:
                problems.append(f"Metrics.{name} dtype {leaf.dtype} != "
                                f"int32 (kernel wire lanes are i32)")
            if leaf.shape != want_shape[name]:
                problems.append(f"Metrics.{name} shape {leaf.shape} != "
                                f"{want_shape[name]}")
    cs = clients_init(cfg_on, g)
    for name in ClientState._fields:
        leaf = getattr(cs, name)
        if leaf.dtype != jnp.int32:
            problems.append(f"ClientState.{name} dtype {leaf.dtype} != i32")
        if leaf.shape != (g, cfg_on.client_slots):
            problems.append(f"ClientState.{name} shape {leaf.shape} != "
                            f"{(g, cfg_on.client_slots)}")
    f = flight_init(g)
    for name in Flight._fields:
        leaf = getattr(f, name)
        if leaf.dtype != jnp.int32:
            problems.append(f"Flight.{name} dtype {leaf.dtype} != int32")
        if leaf.shape != (RING, g):
            problems.append(f"Flight.{name} shape {leaf.shape} != "
                            f"{(RING, g)}")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"METRIC PARITY DRIFT: {p}")
        return 1
    print("metric parity ok: Metrics == KMetrics == METRIC_LEAVES "
          "(client lanes gated); Flight == FLIGHT_LEAVES; "
          "ClientState == CLIENT_LEAVES; all leaves i32 at wire shapes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
