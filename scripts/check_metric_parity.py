"""Static metric-surface parity check: the XLA path's `Metrics`, the
kernel's `KMetrics`, its wire order `METRIC_LEAVES`, and the flight
recorder's `Flight`/`FLIGHT_LEAVES` must stay name-, dtype-, order-,
and shape-aligned — the bench promotion gates and kfinish's name-based
wire indexing all assume it. Exits nonzero on any drift; runs in tier-1
via tests/test_obs.py (fast: builds two host-side pytrees, no jit).

Since r10 this is a thin wrapper over ONE source of truth: the
engine-contract auditor's metric-parity pass
(`raft_tpu.analysis.contracts.metric_parity_problems` — DESIGN.md
§11). `scripts/static_audit.py` / `raft-tpu-audit` run this pass plus
the full contract surface (wire registries, shard rule, checkpoint
coverage, derived byte model, purity lint).

    python scripts/check_metric_parity.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

# Static check — never let the import initialize a real accelerator.
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def check() -> list[str]:
    """Returns the list of parity problems (empty = aligned)."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from raft_tpu.analysis.contracts import metric_parity_problems
    return metric_parity_problems()


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(f"METRIC PARITY DRIFT: {p}")
        return 1
    print("metric parity ok: Metrics == KMetrics == METRIC_LEAVES "
          "(client lanes gated); Flight == FLIGHT_LEAVES; "
          "ClientState == CLIENT_LEAVES; all leaves i32 at wire shapes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
