"""Kernel feature-mix fuzz sweep: the DESIGN.md §7a differential as a
runnable artifact (VERDICT r05 Missing #2 — the original sweep was run
ad hoc and committed as prose; evidence that cannot be re-run decays
the moment the code changes).

For each universe the Pallas fused-chunk engine (sim/pkernel.py) and
the XLA scan path (sim.run) simulate the SAME config+seed and must end
bit-identical on the FULL State pytree and the FULL Metrics pytree
(committed / leaderless / elections / latency histogram / max_latency).
Any divergence prints the universe and exits nonzero.

Universe construction: k cycles {3, 4, 5} and L cycles {16, 32} across
a 6-row pairwise covering array over the five feature/fault factors
(prevote x reconfig x transfer x scheduled-reads x partition) — every
unordered factor pair exhibits all four on/off combinations somewhere
in the sweep (asserted at startup, so the covering property cannot
silently rot). All universes carry baseline crash + drop churn so
elections, truncations, and the fast-backup path actually execute.

Run on the real TPU (the driver's job):
    python scripts/kernel_sweep.py
CPU smoke (interpret mode, small shape — minutes per universe):
    python scripts/kernel_sweep.py --interpret --groups 8 --ticks 48
Sharded kernel (`--devices N`, DESIGN.md §9): every universe runs
through the shard_map'd engine (parallel/kmesh.py) instead of the
single-device kstep. On a box with fewer devices than N the script
re-execs itself on an N-device virtual CPU platform (the same
xla_force_host_platform_device_count trick tests/conftest.py and the
dryrun use), so the pairwise feature x fault matrix also covers the
sharded path:
    python scripts/kernel_sweep.py --devices 8 --interpret --groups 16 --ticks 48
"""

from __future__ import annotations

import argparse
import itertools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

import jax

from raft_tpu import sim
from raft_tpu.config import RaftConfig
from raft_tpu.sim import pkernel
from raft_tpu.sim.run import metrics_init, run, unsafe_groups
from raft_tpu.utils.trees import trees_equal_why

# Factor order: (prevote, reconfig, transfer, reads, partition).
# 6-row pairwise covering array over 5 boolean factors (verified by
# _check_pairwise at startup).
FACTORS = ("prevote", "reconfig", "transfer", "reads", "partition")
ROWS = (
    (0, 0, 0, 0, 0),
    (1, 1, 1, 1, 1),
    (1, 1, 0, 0, 1),
    (1, 0, 1, 1, 0),
    (0, 1, 1, 0, 0),
    (0, 0, 0, 1, 1),
)


def _check_pairwise(rows):
    for i, j in itertools.combinations(range(len(FACTORS)), 2):
        seen = {(r[i], r[j]) for r in rows}
        if len(seen) != 4:
            raise AssertionError(
                f"covering array broken: factors {FACTORS[i]} x "
                f"{FACTORS[j]} only hit {sorted(seen)}")


def sweep_configs(base_seed: int, clients: bool = False,
                  packed: bool = False):
    """The 6 sweep universes: k in {3,4,5} and L in {16,32} cycle
    across the covering-array rows, seeds derived from base_seed. With
    `clients` (the `--clients` axis, ISSUE r09) every universe swaps
    the scheduled fire-hose for open-loop exactly-once session traffic
    (sessions=True, cmds_per_tick=0, retrying clients) — the same
    pairwise feature x fault matrix, driven by duplicate-risk client
    ops through BOTH engines. With `packed` (the `--packed` axis,
    ISSUE r13) every universe runs the kernel on the packed + donated
    wire (pack_bools + pack_ring + alias_wire) — packing is a
    chunk-boundary re-encode, so the full State + Metrics bit-identity
    gate applies UNCHANGED, and the matrix becomes packed x features x
    faults pairwise evidence."""
    ks = (3, 4, 5)
    ls = (16, 32)
    cl = {}
    if clients:
        cl = dict(sessions=True, cmds_per_tick=0, client_rate=0.25,
                  client_slots=3, client_retry_backoff=6)
    if packed:
        cl.update(pack_bools=True, pack_ring=True, alias_wire=True)
    for n, row in enumerate(ROWS):
        prevote, reconfig, transfer, reads, partition = row
        yield RaftConfig(
            seed=base_seed + n,
            k=ks[n % 3],
            log_cap=ls[n % 2],
            prevote=bool(prevote),
            reconfig_prob=0.8 if reconfig else 0.0, reconfig_epoch=16,
            transfer_prob=0.7 if transfer else 0.0, transfer_epoch=24,
            read_every=4 if reads else 0,
            partition_prob=0.2 if partition else 0.0, partition_epoch=16,
            crash_prob=0.15, crash_epoch=24, drop_prob=0.04,
            **cl,
        )


def run_universe(cfg: RaftConfig, n_groups: int, ticks: int,
                 interpret: bool, devices: int = 1,
                 stream: bool = False):
    """(ok, detail, seconds, unsafe) for one universe's kernel-vs-XLA
    check. `unsafe` counts groups whose per-tick safety bit dropped —
    each universe doubles as an n_groups x ticks safety soak, so the
    sweep log is soak evidence, not just divergence evidence. With
    `devices > 1` the kernel half runs shard_map'd over a device mesh
    (parallel/kmesh.py) — the XLA reference stays unsharded, so the
    comparison also certifies that sharding is invisible. With
    `stream` (the `--stream` axis, ISSUE r16) the kernel half runs
    through the cohort scheduler (parallel/cohort.py) at
    cohort_blocks=1 and >=2 launches per window, so the comparison
    certifies that host<->HBM paging is invisible too. `stream` AND
    `devices > 1` compose (r17): the kernel half runs
    `prun_streamed_sharded` — every device pages its own whole-block
    window slice — so the comparison certifies that SHARDED paging is
    invisible as well."""
    t0 = time.perf_counter()
    st0 = sim.init(cfg, n_groups=n_groups)
    stx, mx = run(cfg, st0, ticks, 0,
                  metrics_init(n_groups, clients=cfg.clients_u32 != 0))
    if stream:
        import dataclasses

        from raft_tpu.parallel import cohort
        scfg = dataclasses.replace(cfg, stream_groups=True,
                                   cohort_blocks=1)
        if devices > 1:
            from raft_tpu import parallel
            mesh = parallel.make_mesh(devices)
            stp, mp = cohort.prun_streamed_sharded(
                scfg, st0, ticks, mesh, interpret=interpret,
                chunk_ticks=max(1, ticks // 2))
        else:
            stp, mp = cohort.prun_streamed(scfg, st0, ticks,
                                           interpret=interpret,
                                           chunk_ticks=max(1, ticks // 2))
    elif devices > 1:
        from raft_tpu import parallel
        from raft_tpu.parallel import kmesh
        mesh = parallel.make_mesh(devices)
        stp, mp = kmesh.prun_sharded(cfg, st0, ticks, mesh,
                                     interpret=interpret)
    else:
        stp, mp = pkernel.prun(cfg, st0, ticks, interpret=interpret)
    s_ok, s_why = trees_equal_why(stx, stp)
    m_ok, m_why = trees_equal_why(
        mx, mp, names=list(type(mx)._fields))
    unsafe = unsafe_groups(mx)
    dt = time.perf_counter() - t0
    eo_ok, eo_why = True, ""
    if cfg.clients_u32:
        # Exactly-once endpoint accounting (clients/workload.py) on top
        # of the per-tick fold already latched into `unsafe`: a
        # double-apply shows up as rc != 0 either way.
        from raft_tpu.clients import exactly_once_report
        eo_ok, eo_why = exactly_once_report(cfg, stx, mx)
    if s_ok and m_ok and eo_ok:
        detail = "bit-identical (state + metrics incl. histogram + safety bit)"
        if cfg.clients_u32:
            import numpy as np
            detail += (f"; {eo_why}; "
                       f"{int(np.asarray(stx.clients.retries).sum())} "
                       f"duplicate-risk retries")
        return (True, detail, dt, unsafe)
    return (False, f"state: {s_why or 'ok'}; metrics: {m_why or 'ok'}; "
            f"exactly-once: {eo_why or 'ok'}", dt, unsafe)


def nemesis_cell(base_seed: int, n_groups: int, ticks: int,
                 interpret: bool, devices: int = 1) -> int:
    """The --nemesis cells (ISSUE r14, grown r20): canonical nemesis
    programs through ALL THREE engines over a faulted universe —

    - `gray-mix`: the r14 fail-SLOW acceptance gate (slow-but-alive
      follower + asymmetric flaky link);
    - `disk-full` / `compaction`: each r20 storage-pressure clause
      kind ALONE, so a parity break blames one schedule evaluator;
    - `pressure-mix+admission`: the combined §19 program with bounded
      admission-queue client traffic riding on top — the graceful-
      degradation path (durable-prefix NACKs, ring backpressure,
      definitive sheds) exercised end to end with the exactly-once
      ledger checked.

    Per cell: CPU oracle vs the XLA scan, lockstep on the trace
    surface per node per tick (the first min(8, G) groups — groups
    are independent and identity is the global group id, so the
    oracle slice of a larger batched run is exact); then XLA vs the
    Pallas kernel (sharded when --devices > 1) on the FULL State +
    Metrics pytrees, bit-identical. rc != 0 on any divergence or
    safety violation."""
    from raft_tpu import nemesis
    from raft_tpu.obs.triage import oracle_divergence

    ticks = max(ticks, 120)   # the acceptance gate is a >=120-tick soak
    base = dict(seed=base_seed, k=3, log_cap=8, compact_every=4,
                drop_prob=0.03, crash_prob=0.1, crash_epoch=24)
    admission = dict(sessions=True, cmds_per_tick=0, client_rate=0.3,
                     client_slots=2, client_queue_cap=4)
    cells = (
        ("gray-mix", RaftConfig(**base, nemesis=nemesis.gray_mix(ticks))),
        ("disk-full", RaftConfig(**base, nemesis=nemesis.program(
            nemesis.disk_full_follower(0, ticks, p=0.8, epoch=8)))),
        ("compaction", RaftConfig(**base, nemesis=nemesis.program(
            nemesis.compaction_pressure(0, ticks, p=0.5, epoch=8)))),
        ("pressure-mix+admission",
         RaftConfig(**base, **admission,
                    nemesis=nemesis.pressure_mix(ticks))),
    )
    rc = 0
    for name, cfg in cells:
        print(f"[nemesis:{name}] program "
              f"{nemesis.program_hash(cfg.nemesis)}: "
              f"{nemesis.describe(cfg.nemesis)}", flush=True)
        t0 = time.perf_counter()
        g_oracle = min(8, n_groups)
        div = oracle_divergence(cfg, n_groups, ticks,
                                oracle_groups=g_oracle)
        if div is not None:
            print(f"[nemesis:{name}] ORACLE vs XLA DIVERGED at "
                  f"t={div['tick']} group={div['group']} "
                  f"node={div['node']} field={div['field']}: "
                  f"cpu={div['cpu']} jax={div['jax']}", flush=True)
            rc = 1
            continue
        print(f"[nemesis:{name}] oracle == xla per node per tick "
              f"({g_oracle} groups x {ticks} ticks)", flush=True)

        ok, detail, dt, unsafe = run_universe(cfg, n_groups, ticks,
                                              interpret, devices)
        tag = "ok" if ok else "DIVERGED"
        safe_tag = "ok" if unsafe == 0 else f"VIOLATED({unsafe} groups)"
        print(f"[nemesis:{name}] xla vs kernel: {tag} safety={safe_tag} "
              f"— {detail} ({time.perf_counter() - t0:.1f}s total)",
              flush=True)
        if not (ok and unsafe == 0):
            rc = 1
    if rc == 0:
        print(f"[nemesis] {len(cells)} programs bit-identical on "
              f"oracle/xla/kernel over {n_groups} groups x {ticks} "
              f"ticks", file=sys.stderr)
    return rc


def _reexec_with_host_devices(n_devices: int) -> int:
    """Re-run this script in a child whose env forces an n-device
    virtual CPU platform BEFORE jax initializes (the flag is read at
    first backend init — same mechanism as __graft_entry__'s dryrun)."""
    import subprocess
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAFT_TPU_SWEEP_REEXEC"] = "1"   # one hop only, never recurse
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return subprocess.run([sys.executable] + sys.argv, env=env).returncode


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=512)
    ap.add_argument("--ticks", type=int, default=120)
    ap.add_argument("--seed", type=int, default=1000,
                    help="base seed; universe n uses seed+n")
    ap.add_argument("--interpret", action="store_true",
                    help="pallas interpret mode (CPU smoke; no TPU)")
    ap.add_argument("--devices", type=int, default=1,
                    help="shard the kernel over this many devices "
                    "(re-execs onto a virtual CPU platform if the box "
                    "has fewer)")
    ap.add_argument("--clients", action="store_true",
                    help="drive every universe with open-loop "
                    "exactly-once session traffic instead of the "
                    "scheduled fire-hose (sessions x fault matrix; "
                    "exit nonzero on divergence or double-apply)")
    ap.add_argument("--packed", action="store_true",
                    help="run every universe's kernel on the r13 "
                    "packed + donated wire (pack_bools + pack_ring + "
                    "alias_wire) — packed x feature x fault pairwise "
                    "cells, same full State+Metrics bit-identity gate")
    ap.add_argument("--nemesis", action="store_true",
                    help="run the nemesis cells instead of the "
                    "pairwise matrix: the canonical gray-failure mix, "
                    "each r20 storage-pressure kind alone, and the "
                    "pressure mix with bounded-admission client "
                    "traffic — each through oracle, XLA, and the "
                    "kernel over a >=120-tick faulted universe; "
                    "rc != 0 on any divergence")
    ap.add_argument("--stream", action="store_true",
                    help="run every universe's kernel through the r16 "
                    "cohort scheduler (parallel/cohort.py, "
                    "cohort_blocks=1, >=2 launches per window) — the "
                    "streamed x feature x fault cells, same full "
                    "State+Metrics bit-identity gate against the "
                    "resident XLA reference; composes with --devices N "
                    "(r17): each device pages its own whole-block "
                    "window slice (prun_streamed_sharded)")
    args = ap.parse_args()
    _check_pairwise(ROWS)

    if args.devices > 1 and len(jax.devices()) < args.devices:
        if jax.devices()[0].platform == "tpu":
            # Never swap a real TPU for virtual CPUs: a 4-chip box
            # asked for --devices 8 should say so, not silently
            # validate the wrong hardware (make_mesh's rule).
            print(f"only {len(jax.devices())} TPU chip(s) visible, "
                  f"--devices {args.devices} requested; run with "
                  f"--devices {len(jax.devices())} or on a larger "
                  f"slice", file=sys.stderr)
            return 2
        if os.environ.get("RAFT_TPU_SWEEP_REEXEC"):
            print(f"need {args.devices} devices, still have "
                  f"{len(jax.devices())} after the re-exec (a TPU plugin "
                  f"that ignores JAX_PLATFORMS?)", file=sys.stderr)
            return 2
        return _reexec_with_host_devices(args.devices)

    # Pre-flight engine-contract audit (DESIGN.md §11; cheap —
    # eval_shape + AST only): a sweep verdict over a drifted wire
    # layout would be evidence about the wrong program. AFTER the
    # re-exec branch, so the virtual-device path pays it exactly once.
    from raft_tpu import analysis
    analysis.startup_audit(level="static",
                           log=lambda s: print(s, file=sys.stderr))

    dev = jax.devices()[0]
    print(f"platform: {dev.platform} ({dev.device_kind}); "
          f"{args.groups} groups x {args.ticks} ticks per universe"
          + (f"; kernel sharded over {args.devices} devices"
             if args.devices > 1 else ""),
          file=sys.stderr, flush=True)
    if not args.interpret and dev.platform != "tpu":
        print("no TPU attached: pass --interpret (and a small "
              "--groups/--ticks) for a CPU smoke", file=sys.stderr)
        return 2

    if args.nemesis:
        return nemesis_cell(args.seed, args.groups, args.ticks,
                            args.interpret, args.devices)

    failures = violations = swept = 0
    for n, cfg in enumerate(sweep_configs(args.seed, args.clients,
                                          args.packed)):
        feats = "+".join(f for f, on in zip(FACTORS, ROWS[n]) if on) \
            or "faults-only"
        if args.clients:
            feats += "+clients"
        if args.packed:
            feats += "+packed"
        if args.stream:
            feats += "+streamed"
        # Sweep universes carry no flight ring: budget the flight-off
        # model, matching run_universe's flightless prun/prun_sharded.
        if not pkernel.supported(cfg, args.groups, args.devices,
                                 with_flight=False):
            print(f"[{n}] k={cfg.k} L={cfg.log_cap} {feats}: UNSUPPORTED "
                  f"shape (skipped)", flush=True)
            continue
        ok, detail, dt, unsafe = run_universe(cfg, args.groups, args.ticks,
                                              args.interpret, args.devices,
                                              stream=args.stream)
        tag = "ok" if ok else "DIVERGED"
        safe_tag = "ok" if unsafe == 0 else f"VIOLATED({unsafe} groups)"
        print(f"[{n}] seed={cfg.seed} k={cfg.k} L={cfg.log_cap} "
              f"{feats}: {tag} safety={safe_tag} — {detail} ({dt:.1f}s)",
              flush=True)
        failures += 0 if ok else 1
        violations += 0 if unsafe == 0 else 1
        swept += 1
    if failures or violations:
        print(f"{failures} universe(s) DIVERGED, {violations} with safety "
              f"violations", file=sys.stderr)
        return 1
    print(f"sweep clean: every universe bit-identical; per-tick safety "
          f"bit held across all {swept} universes "
          f"({args.groups} groups x {args.ticks} ticks each"
          + (f", kernel sharded over {args.devices} devices)"
             if args.devices > 1 else ")"),
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
