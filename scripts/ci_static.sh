#!/usr/bin/env bash
# The pre-push gate (DESIGN.md §17, README "Verification"): every
# chip-free verification pass in one command, sized to run in well
# under a minute on a laptop —
#
#   - engine-contract audit (pytrees vs kernel wire registries vs shard
#     rule vs checkpoint format + derived byte model),
#   - purity/determinism lint over the full tick + scheduler surface,
#   - depth-limited bounded model-checker smoke (exhaustive clean
#     oracle at tiny scope + a seeded-mutant canary kill),
#   - stream-scheduler hazard prover (real r16/r17 pipelines over the
#     bound grid + synthetic negatives caught with file:line),
#   - bench-history regression gate (r19): the checked-in perf
#     trajectory vs scripts/bench_baseline.json — known fades are
#     allowlisted, any NEW regression (or a known one deepening) fails.
#
# The first four are `static_audit --level deep` (analysis/cli.py);
# rc != 0 names the violated contract/invariant/regression. Run before
# pushing:
#
#   scripts/ci_static.sh
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
python scripts/bench_history.py --check --threshold 0.15 \
    --baseline scripts/bench_baseline.json >/dev/null
exec python scripts/static_audit.py --level deep "$@"
