#!/usr/bin/env bash
# The pre-push gate (DESIGN.md §17, README "Verification"): every
# chip-free verification pass in one command, sized to run in well
# under a minute on a laptop —
#
#   - engine-contract audit (pytrees vs kernel wire registries vs shard
#     rule vs checkpoint format + derived byte model),
#   - purity/determinism lint over the full tick + scheduler surface,
#   - depth-limited bounded model-checker smoke (exhaustive clean
#     oracle at tiny scope + a seeded-mutant canary kill),
#   - stream-scheduler hazard prover (real r16/r17 pipelines over the
#     bound grid + synthetic negatives caught with file:line).
#
# All four are `static_audit --level deep` (analysis/cli.py); rc != 0
# names the violated contract/invariant. Run before pushing:
#
#   scripts/ci_static.sh
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
exec python scripts/static_audit.py --level deep "$@"
