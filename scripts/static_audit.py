"""Static engine-contract audit: prove the three engines, the kernel
wire model, and the checkpoint format agree — before anything runs
(DESIGN.md §11). Thin wrapper over `raft_tpu.analysis.cli` (also
installed as the `raft-tpu-audit` console script).

    python scripts/static_audit.py            # rc != 0 on any drift
    python scripts/static_audit.py --json     # machine-readable report
    python scripts/static_audit.py --bytes    # per-leaf derived bytes
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

from raft_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
