"""Multi-chip kernel sweep: G x devices grid for the sharded
fused-chunk engine, emitting MULTICHIP_r07.json (ROADMAP item 1 /
DESIGN.md §9).

Grid: G in {200K, 500K, 1M} x devices in {1, 4, 8}. Per cell, on a TPU
host, the sharded kernel (raft_tpu/parallel/kmesh.py) is timed with the
bench's warmup/chunk protocol and gated the bench's way — promotion
requires the FULL State + Metrics pytrees bit-identical to a reference
at the same tick (three-way where feasible: sharded kernel vs
single-device kernel vs XLA scan) and a clean per-tick safety fold.
Cells the per-device HBM budget rejects (`pkernel.supported` mesh-aware
form) are recorded as unsupported with the modeled byte count — that IS
the ceiling probe; a cell that passes the model but dies at runtime
records the error string instead of a number.

On a CPU-only box the grid still comes out, marked rather than omitted:
each cell runs the sharded XLA path (`parallel.run_sharded`) at a
scaled-down shape with `mode: "dryrun"`, and one `interpret_gate` block
runs the shard_map'd Pallas kernel in interpret mode against the
unsharded kernel and the XLA path (the tests/test_kmesh.py shape, so
the compile is warm wherever the suite has run). `promoted` is False
for every such entry. The `predicted` block carries the bytes/group
model and the implied ceilings either way (scripts/layout_probe.py
--bytes-only prints the same numbers with a per-leaf breakdown).

    python scripts/multichip_sweep.py                    # full (TPU)
    python scripts/multichip_sweep.py --quick            # small TPU smoke
    python scripts/multichip_sweep.py --out MULTICHIP_r07.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

G_LIST = (200_000, 500_000, 1_000_000)
D_LIST = (1, 4, 8)
CHUNK = 200          # ticks per kernel launch (bench.py protocol)


# One copy of the virtual-host-platform re-exec (kernel_sweep.py owns
# it; both sweeps guard recursion with RAFT_TPU_SWEEP_REEXEC).
from scripts.kernel_sweep import _reexec_with_host_devices  # noqa: E402


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _predicted(cfg):
    import dataclasses

    from raft_tpu.config import LAYOUT_FIELDS, STREAM_FIELDS
    from raft_tpu.sim import pkernel
    buffers = pkernel._residency_buffers(cfg)
    # r16 streamed residency (DESIGN.md §15): the host-RAM-bound
    # ceilings the cohort scheduler models for this layout, next to the
    # static ones so the artifact carries both sides of the ablation.
    scfg = dataclasses.replace(cfg, stream_groups=True)
    sdials = dataclasses.replace(scfg, pack_bools=True, pack_ring=True,
                                 alias_wire=True, wire_hist=False)
    streamed = {
        "knobs": {k: getattr(cfg, k) for k in STREAM_FIELDS},
        "host_ram_limit_bytes": pkernel.HOST_RAM_LIMIT_BYTES,
        "stream_windows": pkernel._stream_windows(scfg),
        "cohort_hbm_bytes_no_flight":
            pkernel.cohort_hbm_bytes(scfg, with_flight=False),
        "ceiling_groups_no_flight":
            pkernel.streamed_ceiling_groups(scfg, with_flight=False),
        "ceiling_groups_all_dials_no_flight":
            pkernel.streamed_ceiling_groups(sdials, with_flight=False),
        "model": "host RAM holds ONE wire copy of G (whole blocks); "
                 "HBM holds only stream_windows cohort windows — see "
                 "scripts/layout_probe.py for the boundary pins",
        # r17 sharded paging (DESIGN.md §16): per-device ceilings when
        # every chip pages its own whole-block window slice — host RAM
        # is a PER-DEVICE allocation (one host per chip group on a
        # pod), so the modeled ceiling scales with the device axis.
        # Re-derived independently by analysis/bytemodel
        # (hbm.streamed.sharded) and pinned by tests/test_stream_mesh.
        "sharded": {
            str(nd): {
                "ceiling_groups_no_flight":
                    pkernel.streamed_ceiling_groups(
                        scfg, n_devices=nd, with_flight=False),
                "blocks_per_device":
                    pkernel.stream_blocks_per_device(scfg, nd),
                "window_hbm_bytes_per_device":
                    pkernel.cohort_hbm_bytes(
                        scfg, with_flight=False, n_devices=nd),
                "speedup_vs_1dev":
                    pkernel.streamed_ceiling_groups(
                        scfg, n_devices=nd, with_flight=False)
                    / max(1, pkernel.streamed_ceiling_groups(
                        scfg, with_flight=False)),
            } for nd in D_LIST
        },
    }
    out = {
        "wire_bytes_per_group":
            4 * pkernel.wire_words_per_group(cfg, with_flight=True),
        "wire_bytes_per_group_no_flight":
            4 * pkernel.wire_words_per_group(cfg, with_flight=False),
        "hbm_limit_bytes": pkernel.HBM_LIMIT_BYTES,
        # Whole-block ceilings, the same rounding supported() applies —
        # a sweep sized at exactly this G is admitted, not rejected.
        # The bench rides the flight ring (flight-on ceiling); the
        # sweep's own cells are flightless (no-flight ceiling).
        "single_chip_ceiling_groups": pkernel.hbm_ceiling_groups(cfg),
        "single_chip_ceiling_groups_no_flight":
            pkernel.hbm_ceiling_groups(cfg, with_flight=False),
        # r13 layout provenance: the dials this sweep's cfg ran with,
        # and the ceiling every dial at once would model (the
        # layout_probe --ablate headline).
        "layout": {k: getattr(cfg, k) for k in LAYOUT_FIELDS},
        "residency_buffers": buffers,
        "single_chip_ceiling_groups_all_dials":
            pkernel.hbm_ceiling_groups(dataclasses.replace(
                cfg, pack_bools=True, pack_ring=True, alias_wire=True,
                wire_hist=False), with_flight=False),
        "model": f"{buffers}x resident wire copies "
                 f"({'donated' if buffers == 1 else 'in + out buffers'}) "
                 "x padded groups; see scripts/layout_probe.py "
                 "--ablate for the per-encoding breakdown",
        "streamed": streamed,
    }
    return out


def _hist_comparable(cfg, m_ref, m_ker):
    """Under the wire_hist dial the kernel tracks no histogram rows
    (its Metrics pass the caller's base through), so the [H]-row leaves
    are not a differential surface: substitute the kernel's rows into
    the reference copy so trees_equal_why still covers every OTHER
    metric leaf bit-for-bit. Identity when the dial is on."""
    if cfg.wire_hist:
        return m_ref
    sub = {"hist": m_ker.hist}
    if m_ref.client_hist is not None:
        sub["client_hist"] = m_ker.client_hist
    return m_ref._replace(**sub)


def _gate(cfg, n_groups, ticks, mesh, interpret):
    """Three-way state_identical gate at (n_groups, ticks): sharded
    kernel vs single-device kernel (when one device can hold G) vs the
    XLA scan. Returns (verdicts dict, unsafe count, sharded Metrics)."""
    import numpy as np

    from raft_tpu import sim
    from raft_tpu.parallel import kmesh
    from raft_tpu.sim import pkernel
    from raft_tpu.sim.run import run, unsafe_groups
    from raft_tpu.utils.trees import trees_equal_why

    st0 = sim.init(cfg, n_groups=n_groups)
    leaves, g = kmesh.kinit_sharded(cfg, st0, mesh)
    leaves = kmesh.kstep_sharded(cfg, leaves, 0, ticks, mesh,
                                 interpret=interpret)
    st_sh, m_sh = pkernel.kfinish(cfg, leaves, g)
    # The psum'd boundary verdicts must agree with the host-side fold;
    # computed FIRST so the sharded wire buffers can be dropped before
    # the single-device references run (at the flagship shapes those
    # need every byte of one chip's HBM for themselves).
    gm = kmesh.kglobal_sharded(cfg, leaves, g, mesh)
    assert int(gm.elections) == int(m_sh.elections)
    # The psum rides i32 lanes (x64 is off on-device), so compare
    # modulo 2^32: at flagship shapes (1M groups x long gate runs) the
    # true total can pass 2^31 and the device counter wraps — that is
    # an i32 representation artifact, not a parity failure. Promoted
    # throughput numbers always come from the int64 host-side counters
    # (GlobalKMetrics docstring).
    host_rounds = int(np.asarray(m_sh.committed).astype(np.int64).sum())
    assert int(gm.rounds) & 0xFFFFFFFF == host_rounds & 0xFFFFFFFF
    assert int(gm.unsafe) == unsafe_groups(m_sh)
    del leaves
    verdicts = {}
    if mesh.size > 1 and pkernel.supported(cfg, n_groups, 1,
                                           with_flight=False):
        try:
            st_1, m_1 = pkernel.prun(cfg, st0, ticks, interpret=interpret)
            ok_s, why_s = trees_equal_why(st_sh, st_1)
            ok_m, why_m = trees_equal_why(
                m_sh, m_1, names=list(type(m_sh)._fields))
            verdicts["vs_kernel_1dev"] = bool(ok_s and ok_m)
            if not (ok_s and ok_m):
                log(f"    1dev-kernel mismatch: {why_s or why_m}")
        except Exception as e:
            verdicts["vs_kernel_1dev"] = f"error: {type(e).__name__}"
    try:
        st_x, m_x = run(cfg, st0, ticks)
        m_x = _hist_comparable(cfg, m_x, m_sh)
        ok_s, why_s = trees_equal_why(st_x, st_sh)
        ok_m, why_m = trees_equal_why(
            m_x, m_sh, names=list(type(m_x)._fields))
        verdicts["vs_xla"] = bool(ok_s and ok_m)
        if not (ok_s and ok_m):
            log(f"    xla mismatch: {why_s or why_m}")
    except Exception as e:   # XLA at 1M groups can OOM where the kernel fits
        verdicts["vs_xla"] = f"error: {type(e).__name__}"
    bool_verdicts = [v for v in verdicts.values() if isinstance(v, bool)]
    # Tri-state: True = every reference that ran matched; False = a
    # real divergence; None = NO reference could run (e.g. both OOM at
    # the 1M flagship cell) — unknown is not a failure, but it is
    # never promotable either.
    state_identical = (all(bool_verdicts) if bool_verdicts else None)
    return ({"state_identical": state_identical, **verdicts},
            unsafe_groups(m_sh), m_sh)


def _time_cell(cfg, n_groups, ticks, mesh):
    """Bench-protocol timing: 2 warmup chunks (compiles), then timed
    chunks; rounds/s from the int64 host-side committed delta."""
    from raft_tpu import sim
    from raft_tpu.parallel import kmesh
    from raft_tpu.sim import pkernel

    leaves, g = kmesh.kinit_sharded(cfg, sim.init(cfg, n_groups=n_groups),
                                    mesh)
    t0 = time.perf_counter()
    leaves = kmesh.kstep_sharded(cfg, leaves, 0, CHUNK, mesh)
    pkernel.kcommitted(cfg, leaves, g)
    leaves = kmesh.kstep_sharded(cfg, leaves, CHUNK, CHUNK, mesh)
    base = pkernel.kcommitted(cfg, leaves, g)
    warmup_s = time.perf_counter() - t0
    n_chunks = max(1, ticks // CHUNK)
    start = time.perf_counter()
    for c in range(n_chunks):
        leaves = kmesh.kstep_sharded(cfg, leaves, (c + 2) * CHUNK, CHUNK,
                                     mesh)
    rounds = pkernel.kcommitted(cfg, leaves, g) - base   # fetch closes the timer
    elapsed = time.perf_counter() - start
    _, met = pkernel.kfinish(cfg, leaves, g)
    from raft_tpu.sim.run import unsafe_groups
    return {"rounds": rounds, "timed_ticks": n_chunks * CHUNK,
            "timed_wall_s": round(elapsed, 3),
            "warmup_wall_s": round(warmup_s, 3),
            "rounds_per_sec": round(rounds / elapsed, 1),
            "timed_unsafe_groups": unsafe_groups(met)}


def tpu_cell(cfg, n_groups, n_devices, ticks, gate_ticks):
    """One (G, D) grid cell on real chips."""
    from raft_tpu import parallel
    from raft_tpu.sim import pkernel

    cell = {"groups": n_groups, "devices": n_devices, "mode": "tpu",
            "promoted": False}
    # The sweep's runs carry no flight ring, so gate and report the
    # flight-off model — the flight-on budget would reject the
    # 1.03M-1.27M-group band this probe exists to measure.
    if not pkernel.supported(cfg, n_groups, n_devices, with_flight=False):
        cell["status"] = "unsupported"
        cell["hbm_bytes_per_device"] = pkernel.hbm_bytes(
            cfg, n_groups, n_devices, with_flight=False)
        cell["hbm_limit_bytes"] = pkernel.HBM_LIMIT_BYTES
        log(f"  [{n_groups}g x {n_devices}d] unsupported: modeled "
            f"{cell['hbm_bytes_per_device']:,} B/device > budget")
        return cell
    try:
        mesh = parallel.make_mesh(n_devices)
        verdicts, unsafe, _ = _gate(cfg, n_groups, gate_ticks, mesh,
                                    interpret=False)
        cell.update(verdicts)
        cell["gate_ticks"] = gate_ticks
        cell["safety_ok"] = unsafe == 0
        cell["unsafe_groups"] = unsafe
        cell.update(_time_cell(cfg, n_groups, ticks, mesh))
        cell["safety_ok"] = cell["safety_ok"] \
            and cell["timed_unsafe_groups"] == 0
        cell["promoted"] = bool(cell["state_identical"]
                                and cell["safety_ok"])
        cell["status"] = "ok"
        log(f"  [{n_groups}g x {n_devices}d] "
            f"{cell['rounds_per_sec']:,.0f} rounds/s "
            f"(state_identical={cell['state_identical']} "
            f"safety_ok={cell['safety_ok']})")
    except Exception as e:
        # THE ceiling probe: a cell the model admits but the runtime
        # rejects names its killer here (Mosaic OOM, HBM allocator, ...).
        cell["status"] = f"error: {type(e).__name__}: {e}"
        log(f"  [{n_groups}g x {n_devices}d] FAILED: {cell['status']}")
    return cell


# CPU stand-in universe for dryrun cells AND the interpret gate: the
# shared kmesh.faulted_64_cfg() k=3/L=8 shape. Deliberately NOT the
# headline config — a k=5/L=32 scan program costs many minutes of XLA
# compile on the CPU box (20+ in its slow mode), while this one is
# seconds-to-a-minute and warm in tests/.jax_cache wherever the test
# suite or dryrun has run. The cell records the scaled config next to
# the requested grid coordinates.
def _dry_cfg():
    from raft_tpu.parallel import kmesh
    return kmesh.faulted_64_cfg()


def dryrun_cell(n_groups, n_devices, dry_ticks):
    """CPU stand-in for a grid cell: the sharded XLA path at the scaled
    universe, gated against the unsharded XLA run. Marks itself."""
    import numpy as np

    from raft_tpu import parallel, sim
    from raft_tpu.sim.run import run
    from raft_tpu.utils.trees import trees_equal_why

    cfg = _dry_cfg()
    cell = {"groups": n_groups, "devices": n_devices, "mode": "dryrun",
            "promoted": False,
            "run": {"groups": cfg.n_groups, "ticks": dry_ticks,
                    "k": cfg.k, "log_cap": cfg.log_cap,
                    "engine": "xla-shard_map"}}
    t0 = time.perf_counter()
    mesh = parallel.make_mesh(n_devices)
    st = parallel.shard_state(sim.init(cfg), mesh)
    st, gm = parallel.run_sharded(cfg, st, dry_ticks, mesh)
    ref, m_ref = run(cfg, sim.init(cfg), dry_ticks)
    ok, why = trees_equal_why(ref, st)
    cell["state_identical"] = bool(
        ok and int(gm.rounds) == int(np.asarray(m_ref.committed).sum()))
    if not ok:
        log(f"    dryrun mismatch: {why}")
    cell["safety_ok"] = int(gm.unsafe) == 0
    cell["rounds"] = int(gm.rounds)
    cell["wall_s"] = round(time.perf_counter() - t0, 3)
    cell["status"] = "ok"
    log(f"  [{n_groups}g x {n_devices}d] dryrun at {cfg.n_groups}g x "
        f"{dry_ticks}t: state_identical={cell['state_identical']} "
        f"safety_ok={cell['safety_ok']}")
    return cell


def interpret_gate(n_devices: int, dials: dict | None = None):
    """The sharded-KERNEL differential a CPU box can afford: interpret
    mode at the tests/test_kmesh.py shape (warm compile cache), 3-way
    vs the unsharded kernel and the XLA path. `dials` (r13 layout
    knobs) re-runs it at the requested packed layout — a fresh
    interpret compile, but the only sharded-kernel evidence a --pack
    sweep can produce off-TPU."""
    import dataclasses

    from raft_tpu import parallel

    cfg = _dry_cfg()
    if dials:
        cfg = dataclasses.replace(cfg, **dials)
    mesh = parallel.make_mesh(n_devices)
    t0 = time.perf_counter()
    verdicts, unsafe, _ = _gate(cfg, cfg.n_groups, 48, mesh,
                                interpret=True)
    return {"mode": "interpret", "devices": n_devices,
            "groups": cfg.n_groups, "ticks": 48, **verdicts,
            "safety_ok": unsafe == 0,
            "wall_s": round(time.perf_counter() - t0, 3)}


def streamed_gate(dials: dict | None = None):
    """The r16 cohort-paging differential a CPU box can afford
    (DESIGN.md §15): interpret mode at the shared faulted-64 shape,
    THREE-WAY — the streamed engine (parallel/cohort.py,
    cohort_blocks=1, two launches per window) vs the resident kernel
    vs the XLA scan, full State + Metrics bit-identical. The streamed
    column of this sweep's artifact: paging must be invisible before
    any streamed throughput number means anything."""
    import dataclasses

    from raft_tpu import sim
    from raft_tpu.parallel import cohort
    from raft_tpu.sim import pkernel
    from raft_tpu.sim.run import run, unsafe_groups
    from raft_tpu.utils.trees import trees_equal_why

    cfg = _dry_cfg()
    if dials:
        cfg = dataclasses.replace(cfg, **dials)
    scfg = dataclasses.replace(cfg, stream_groups=True, cohort_blocks=1)
    ticks = 48
    t0 = time.perf_counter()
    st0 = sim.init(cfg)
    st_s, m_s = cohort.prun_streamed(scfg, st0, ticks, interpret=True,
                                     chunk_ticks=ticks // 2)
    verdicts = {}
    st_k, m_k = pkernel.prun(cfg, st0, ticks, interpret=True)
    ok_s, why_s = trees_equal_why(st_k, st_s)
    ok_m, why_m = trees_equal_why(m_k, m_s,
                                  names=list(type(m_k)._fields))
    verdicts["vs_kernel_resident"] = bool(ok_s and ok_m)
    if not (ok_s and ok_m):
        log(f"    resident-kernel mismatch: {why_s or why_m}")
    st_x, m_x = run(cfg, st0, ticks)
    m_x = _hist_comparable(cfg, m_x, m_s)
    ok_s, why_s = trees_equal_why(st_x, st_s)
    ok_m, why_m = trees_equal_why(m_x, m_s,
                                  names=list(type(m_x)._fields))
    verdicts["vs_xla"] = bool(ok_s and ok_m)
    if not (ok_s and ok_m):
        log(f"    xla mismatch: {why_s or why_m}")
    return {"mode": "interpret-streamed", "engine": cohort.ENGINE,
            "groups": cfg.n_groups, "ticks": ticks, "cohort_blocks": 1,
            "state_identical": all(verdicts.values()), **verdicts,
            "safety_ok": unsafe_groups(m_s) == 0,
            "wall_s": round(time.perf_counter() - t0, 3)}


def streamed_sharded_gate(n_devices: int = 2, dials: dict | None = None):
    """The r17 SHARDED cohort-paging differential a CPU box can afford
    (DESIGN.md §16): interpret mode, THREE-WAY — `prun_streamed_sharded`
    (every device paging its own whole-block window slice) vs the
    RESIDENT sharded kernel (`kmesh.prun_sharded`) vs the recorded XLA
    scan, full State + full Metrics + flight ring bit-identical. The
    shape is deliberately multi-cohort AND multi-launch (G=2500 pads to
    4 blocks -> 2 windows of 2 blocks at cohort_blocks=2 x 2 devices;
    chunk_ticks=ticks/2 -> 2 launches per window) so the differential
    exercises window hand-off, per-device slicing, the staging pool,
    and mid-window re-launch — not just a single resident pass."""
    import dataclasses

    from raft_tpu import parallel, sim
    from raft_tpu.obs.recorder import flight_init, run_recorded
    from raft_tpu.parallel import cohort, kmesh
    from raft_tpu.sim.run import unsafe_groups
    from raft_tpu.utils.trees import trees_equal_why

    cfg = _dry_cfg()
    if dials:
        cfg = dataclasses.replace(cfg, **dials)
    scfg = dataclasses.replace(cfg, stream_groups=True, cohort_blocks=2)
    n_groups, ticks = 2500, 24
    mesh = parallel.make_mesh(n_devices)
    t0 = time.perf_counter()
    st0 = sim.init(cfg, n_groups=n_groups)
    st_s, m_s, f_s = cohort.prun_streamed_sharded(
        scfg, st0, ticks, mesh, interpret=True,
        flight=flight_init(n_groups), chunk_ticks=ticks // 2)
    verdicts = {}
    st_k, m_k, f_k = kmesh.prun_sharded(cfg, st0, ticks, mesh,
                                        interpret=True,
                                        flight=flight_init(n_groups))
    ok = [trees_equal_why(st_k, st_s),
          trees_equal_why(m_k, m_s, names=list(type(m_k)._fields)),
          trees_equal_why(f_k, f_s)]
    verdicts["vs_kernel_sharded_resident"] = all(o for o, _ in ok)
    for o, why in ok:
        if not o:
            log(f"    resident-sharded mismatch: {why}")
    st_x, m_x, f_x = run_recorded(cfg, st0, ticks,
                                  flight=flight_init(n_groups))
    m_x = _hist_comparable(cfg, m_x, m_s)
    ok = [trees_equal_why(st_x, st_s),
          trees_equal_why(m_x, m_s, names=list(type(m_x)._fields)),
          trees_equal_why(f_x, f_s)]
    verdicts["vs_xla"] = all(o for o, _ in ok)
    for o, why in ok:
        if not o:
            log(f"    xla mismatch: {why}")
    return {"mode": "interpret-streamed-sharded",
            "engine": cohort.sharded_engine(n_devices),
            "groups": n_groups, "ticks": ticks, "cohort_blocks": 2,
            "devices": n_devices, "launches_per_window": 2,
            "state_identical": all(verdicts.values()), **verdicts,
            "safety_ok": unsafe_groups(m_s) == 0,
            "wall_s": round(time.perf_counter() - t0, 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="MULTICHIP_r07.json")
    ap.add_argument("--ticks", type=int, default=600,
                    help="timed ticks per TPU cell (bench headline: 600)")
    ap.add_argument("--gate-ticks", type=int, default=200,
                    help="ticks for the state_identical gate universe")
    ap.add_argument("--quick", action="store_true",
                    help="TPU smoke: one small G, 200 timed ticks")
    ap.add_argument("--dry-ticks", type=int, default=48,
                    help="ticks for the scaled CPU dryrun cells")
    # r13 wire-layout dials (DESIGN.md §13): the G x D grid probed at a
    # packed/donated/telemetry-dialed layout — the whole point of the
    # dials is moving the very ceiling this sweep exists to measure.
    ap.add_argument("--pack", action="store_true",
                    help="pack the kernel wire (pack_bools + pack_ring)")
    ap.add_argument("--alias", action="store_true",
                    help="input/output-alias + donate the wire buffers "
                         "(halves the residency model)")
    ap.add_argument("--no-hist", action="store_true",
                    help="drop the in-kernel [H]-row histograms from "
                         "the wire (ceiling-run telemetry dial; the "
                         "state gate still runs bit-exact, histogram "
                         "rows are excluded from the differential)")
    args = ap.parse_args()

    max_d = max(D_LIST)
    import jax
    if jax.devices()[0].platform != "tpu" \
            and len(jax.devices()) < max_d:
        if os.environ.get("RAFT_TPU_SWEEP_REEXEC"):
            log(f"still {len(jax.devices())} devices after re-exec")
            return 2
        return _reexec_with_host_devices(max_d)
    if jax.devices()[0].platform != "tpu":
        jax.config.update("jax_platforms", "cpu")
        from raft_tpu.utils import compile_cache
        compile_cache.enable()   # the shared tests/.jax_cache recipe

    from raft_tpu.config import RaftConfig

    cfg = RaftConfig(seed=42,   # the config-5 headline universe
                     pack_bools=args.pack, pack_ring=args.pack,
                     alias_wire=args.alias,
                     wire_hist=not args.no_hist)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n_avail = len(jax.devices())
    log(f"platform: {dev.platform} ({dev.device_kind}), {n_avail} "
        f"device(s); mode: {'tpu' if on_tpu else 'cpu dryrun'}")

    g_list = (1024,) if args.quick else G_LIST
    grid = []
    dry_by_d = {}   # one scaled run per device count; G rows share it
    for n_groups in g_list:
        for n_devices in D_LIST:
            if on_tpu:
                if n_devices > n_avail:
                    grid.append({"groups": n_groups, "devices": n_devices,
                                 "mode": "tpu", "promoted": False,
                                 "status": f"skipped: only {n_avail} "
                                 f"chip(s) attached"})
                    continue
                grid.append(tpu_cell(cfg, n_groups, n_devices,
                                     args.ticks, args.gate_ticks))
            else:
                # The artifact must come out marked, never be aborted
                # (docstring contract) — mirror tpu_cell's per-cell
                # error capture on the CPU path too.
                if n_devices not in dry_by_d:
                    try:
                        dry_by_d[n_devices] = dryrun_cell(
                            n_groups, n_devices, args.dry_ticks)
                    except Exception as e:
                        dry_by_d[n_devices] = {
                            "devices": n_devices, "mode": "dryrun",
                            "promoted": False,
                            "status": f"error: {type(e).__name__}: {e}"}
                        log(f"  [{n_devices}d] dryrun FAILED: "
                            f"{dry_by_d[n_devices]['status']}")
                grid.append({**dry_by_d[n_devices], "groups": n_groups})

    from raft_tpu.config import LAYOUT_FIELDS
    defaults = RaftConfig(seed=42)
    dials = {k: getattr(cfg, k) for k in LAYOUT_FIELDS}
    dialed = any(dials[k] != getattr(defaults, k) for k in LAYOUT_FIELDS)
    gate = None
    if not on_tpu:
        log(f"interpret-mode sharded-kernel gate (8 devices, 64 groups"
            f"{', dialed layout' if dialed else ''}):")
        try:
            gate = interpret_gate(max_d, dials if dialed else None)
            log(f"  state_identical={gate['state_identical']} "
                f"safety_ok={gate['safety_ok']} ({gate['wall_s']}s)")
        except Exception as e:
            # Tri-state convention: an ERROR is recorded evidence
            # (None = unknown), not a divergence verdict (False) — a
            # flaky compile must not read as "the sharded kernel
            # diverged" in the artifact or the exit code.
            gate = {"mode": "interpret", "devices": max_d,
                    "state_identical": None, "safety_ok": None,
                    "status": f"error: {type(e).__name__}: {e}"}
            log(f"  interpret gate FAILED: {gate['status']}")

    sgate = None
    if not on_tpu:
        # The streamed column (r16): three-way state_identical —
        # streamed vs resident kernel vs XLA — at the shared
        # faulted-64 shape, interpret mode.
        log(f"interpret-mode streamed-engine gate (64 groups, 3-way"
            f"{', dialed layout' if dialed else ''}):")
        try:
            sgate = streamed_gate(dials if dialed else None)
            log(f"  state_identical={sgate['state_identical']} "
                f"(vs_kernel_resident={sgate['vs_kernel_resident']} "
                f"vs_xla={sgate['vs_xla']}) "
                f"safety_ok={sgate['safety_ok']} ({sgate['wall_s']}s)")
        except Exception as e:
            # Same tri-state convention as the interpret gate: an
            # ERROR is recorded evidence, never a divergence verdict.
            sgate = {"mode": "interpret-streamed",
                     "state_identical": None, "safety_ok": None,
                     "status": f"error: {type(e).__name__}: {e}"}
            log(f"  streamed gate FAILED: {sgate['status']}")

    ssgate = None
    if not on_tpu:
        # The sharded-streamed column (r17): three-way state_identical
        # — per-device paging vs the RESIDENT sharded kernel vs the
        # recorded XLA scan — full State + Metrics + flight ring, at a
        # multi-window multi-launch shape (2 blocks/window x 2
        # launches/window on a 2-device mesh).
        nd_gate = min(2, n_avail)
        log(f"interpret-mode sharded-streamed gate ({nd_gate} devices, "
            f"2500 groups, 3-way + flight"
            f"{', dialed layout' if dialed else ''}):")
        try:
            ssgate = streamed_sharded_gate(nd_gate,
                                           dials if dialed else None)
            log(f"  state_identical={ssgate['state_identical']} "
                f"(vs_kernel_sharded_resident="
                f"{ssgate['vs_kernel_sharded_resident']} "
                f"vs_xla={ssgate['vs_xla']}) "
                f"safety_ok={ssgate['safety_ok']} ({ssgate['wall_s']}s)")
        except Exception as e:
            # Same tri-state convention: an ERROR is recorded
            # evidence, never a divergence verdict.
            ssgate = {"mode": "interpret-streamed-sharded",
                      "state_identical": None, "safety_ok": None,
                      "status": f"error: {type(e).__name__}: {e}"}
            log(f"  sharded-streamed gate FAILED: {ssgate['status']}")

    out = {
        "schema": 1,
        "source": "scripts/multichip_sweep.py",
        "device": f"{dev.platform}:{dev.device_kind}",
        "n_devices_visible": n_avail,
        "config_seed": cfg.seed,
        "mode": "tpu" if on_tpu else "cpu-dryrun",
        "note": None if on_tpu else (
            "no TPU attached: grid cells ran the sharded XLA path at "
            "scaled shapes (mode=dryrun) and the sharded kernel ran in "
            "interpret mode (interpret_gate); nothing here is a "
            "throughput claim — promoted=false everywhere"),
        "predicted": _predicted(cfg),
        "grid": grid,
        "interpret_gate": gate,
        "streamed_gate": sgate,
        "streamed_sharded_gate": ssgate,
    }
    path = args.out
    if not os.path.isabs(path):
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), path)
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
        fh.write("\n")
    log(f"wrote {path}")
    # Fail on DIVERGENCE or safety violation, never on "no reference
    # could run" (state_identical=None, the unpromotable-unknown at
    # flagship shapes) — that cell's evidence is its recorded error.
    bad = [c for c in grid
           if c.get("status") == "ok"
           and (c.get("state_identical") is False
                or c.get("safety_ok") is False)]
    if gate is not None and (gate["state_identical"] is False
                             or gate["safety_ok"] is False):
        bad.append(gate)   # the only sharded-KERNEL verdict on a CPU box
    if sgate is not None and (sgate["state_identical"] is False
                              or sgate["safety_ok"] is False):
        bad.append(sgate)   # the streamed column's verdict
    if ssgate is not None and (ssgate["state_identical"] is False
                               or ssgate["safety_ok"] is False):
        bad.append(ssgate)   # the sharded-streamed column's verdict
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
