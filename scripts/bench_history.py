#!/usr/bin/env python
"""Bench-history trend table + regression gate (DESIGN.md §12).

Parses every BENCH_r*.json + MULTICHIP_*.json under --root plus the
bench manifest JSONL into one normalized trajectory (obs.history),
prints the per-segment trend table, and — with --check — exits nonzero
when any comparable series' latest point regressed more than
--threshold below its best ancestor. Run on the checked-in snapshots
this prints the r01->r05 trajectory and `--check --threshold 0.15`
flags the r02->r04 XLA throughput fade (7.18M -> 5.07M rounds/s); the
driver gets a real perf gate instead of an unread pile of JSON.

No jax import, no device, no compile — pure file parsing, safe
anywhere (including the tier-1 test tier, tests/test_perf_obs.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tpu.obs import history  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="directory holding BENCH_r*/MULTICHIP_* files")
    ap.add_argument("--manifest", default=None,
                    help="bench manifest JSONL path ('-' to skip; default "
                         "$RAFT_TPU_MANIFEST or <root>/bench_manifest.jsonl)")
    ap.add_argument("--check", action="store_true",
                    help="exit 2 when any series regresses past the "
                         "threshold vs its best ancestor")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative drop that counts as a regression "
                         "(default 0.15)")
    ap.add_argument("--json", action="store_true",
                    help="emit the normalized rows + regressions as JSON "
                         "instead of the table")
    args = ap.parse_args(argv)

    rows = history.load_history(args.root, manifest=args.manifest)
    if not rows:
        print(f"no bench history found under {args.root!r}",
              file=sys.stderr)
        return 1
    regs = history.regressions(rows, threshold=args.threshold)
    if args.json:
        print(json.dumps({"rows": rows, "regressions": regs}, indent=1))
    else:
        print(history.trend_table(rows))
        print(f"{len(rows)} points across "
              f"{len(history.series(rows))} series")
    if regs:
        for r in regs:
            print(f"REGRESSION: {r['segment']} [{r['engine']}] "
                  f"{r['latest']:,.1f} {r['unit']} ({r['latest_source']}) "
                  f"is -{r['drop_pct']}% vs best ancestor "
                  f"{r['best']:,.1f} ({r['best_source']}); "
                  f"threshold {r['threshold_pct']}%", file=sys.stderr)
        if args.check:
            return 2
    elif args.check:
        print(f"regression check clean at threshold {args.threshold}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
