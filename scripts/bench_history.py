#!/usr/bin/env python
"""Bench-history trend table + regression gate (DESIGN.md §12).

Parses every BENCH_r*.json + MULTICHIP_*.json under --root plus the
bench manifest JSONL into one normalized trajectory (obs.history),
prints the per-segment trend table, and — with --check — exits nonzero
when any comparable series' latest point regressed more than
--threshold below its best ancestor. Run on the checked-in snapshots
this prints the r01->r05 trajectory and `--check --threshold 0.15`
flags the r02->r04 XLA throughput fade (7.18M -> 5.07M rounds/s); the
driver gets a real perf gate instead of an unread pile of JSON.

With --baseline FILE (r19: the pre-push wiring in scripts/ci_static.sh
passes scripts/bench_baseline.json), known regressions are an
ALLOWLIST, not a pass: a series already recorded in the baseline only
fails the check when its drop deepens more than BASELINE_SLACK_PCT
beyond the recorded figure — so the historical r02->r04 fade stays
visible in the table but does not wedge the gate shut, while any NEW
regression (a series the baseline has never seen, or a known fade
getting worse) still exits 2. Regenerate the file with
--write-baseline after knowingly accepting a trade-off.

No jax import, no device, no compile — pure file parsing, safe
anywhere (including the tier-1 test tier, tests/test_perf_obs.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tpu.obs import history  # noqa: E402

# A baselined regression may deepen this many percentage POINTS past
# its recorded drop_pct before it counts as new again — absorbs
# measurement jitter between hosts without letting a real further
# slide hide behind the allowlist.
BASELINE_SLACK_PCT = 1.0


def _reg_key(r: dict) -> str:
    """Stable identity of a regressing series in the baseline file."""
    return f"{r['segment']}|{r['engine']}|{r['unit']}"


def split_known(regs: list, baseline: dict) -> tuple[list, list]:
    """(new, known): a regression is KNOWN iff the baseline records its
    series and the drop has not deepened past the recorded figure plus
    BASELINE_SLACK_PCT."""
    new, known = [], []
    for r in regs:
        rec = baseline.get(_reg_key(r))
        if rec is not None and \
                r["drop_pct"] <= rec["drop_pct"] + BASELINE_SLACK_PCT:
            known.append(r)
        else:
            new.append(r)
    return new, known


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=".",
                    help="directory holding BENCH_r*/MULTICHIP_* files")
    ap.add_argument("--manifest", default=None,
                    help="bench manifest JSONL path ('-' to skip; default "
                         "$RAFT_TPU_MANIFEST or <root>/bench_manifest.jsonl)")
    ap.add_argument("--check", action="store_true",
                    help="exit 2 when any series regresses past the "
                         "threshold vs its best ancestor")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative drop that counts as a regression "
                         "(default 0.15)")
    ap.add_argument("--json", action="store_true",
                    help="emit the normalized rows + regressions as JSON "
                         "instead of the table")
    ap.add_argument("--baseline", default=None,
                    help="JSON allowlist of known regressions; with "
                         "--check only NEW regressions (or known ones "
                         f"deepening > {BASELINE_SLACK_PCT} pt past their "
                         "recorded drop) exit 2")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="record the current regressions as the new "
                         "baseline allowlist and exit 0")
    args = ap.parse_args(argv)

    rows = history.load_history(args.root, manifest=args.manifest)
    if not rows:
        print(f"no bench history found under {args.root!r}",
              file=sys.stderr)
        return 1
    regs = history.regressions(rows, threshold=args.threshold)
    if args.write_baseline:
        base = {_reg_key(r): {"drop_pct": r["drop_pct"],
                              "best": r["best"], "latest": r["latest"],
                              "best_source": r["best_source"],
                              "latest_source": r["latest_source"]}
                for r in regs}
        with open(args.write_baseline, "w") as f:
            json.dump({"threshold": args.threshold,
                       "slack_pct": BASELINE_SLACK_PCT,
                       "known": base}, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {len(base)} known regression(s) to "
              f"{args.write_baseline}", file=sys.stderr)
        return 0
    baseline = {}
    if args.baseline:
        with open(args.baseline) as f:
            baseline = json.load(f)["known"]
    new, known = split_known(regs, baseline)
    if args.json:
        print(json.dumps({"rows": rows, "regressions": regs,
                          "new_regressions": new}, indent=1))
    else:
        print(history.trend_table(rows))
        print(f"{len(rows)} points across "
              f"{len(history.series(rows))} series")
    for r in known:
        print(f"known regression (baselined): {r['segment']} "
              f"[{r['engine']}] -{r['drop_pct']}% vs best ancestor",
              file=sys.stderr)
    if new:
        for r in new:
            print(f"REGRESSION: {r['segment']} [{r['engine']}] "
                  f"{r['latest']:,.1f} {r['unit']} ({r['latest_source']}) "
                  f"is -{r['drop_pct']}% vs best ancestor "
                  f"{r['best']:,.1f} ({r['best_source']}); "
                  f"threshold {r['threshold_pct']}%", file=sys.stderr)
        if args.check:
            return 2
    elif args.check:
        extra = f" ({len(known)} baselined)" if known else ""
        print(f"regression check clean at threshold {args.threshold}"
              f"{extra}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
