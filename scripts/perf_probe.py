"""Ablation timing of the batched tick: where does a tick's time go?

Times a 200-tick scanned chunk on the default platform (the real TPU
under the driver) with individual phases of the tick knocked out by
monkeypatching `raft_tpu.sim.step` internals. The tick graph is static
— masks, not branches — so knocking a phase out and diffing wall time
measures that phase's cost including its fusion effects. Results feed
DESIGN.md §7 ("where a tick's time goes") and BENCH history.

Usage: python scripts/perf_probe.py [--groups 50000 100000] [--variants ...]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

import jax
import jax.numpy as jnp

import raft_tpu.sim.step as step_mod
from raft_tpu import sim
from raft_tpu.config import RaftConfig
from raft_tpu.sim.run import Metrics, metrics_init, metrics_update
from raft_tpu.sim.state import I32

CHUNK = 200


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# The module's `tick` is jitted with its own trace cache, which would
# ignore monkeypatched internals — always trace through the raw function.
_raw_tick = step_mod.tick.__wrapped__


def make_runner(cfg, with_metrics: str):
    """with_metrics: 'full' | 'nohist' | 'none'."""

    @jax.jit
    def go(st, m, t0):
        def body(carry, t):
            s, mm = carry
            s = _raw_tick(cfg, s, t)
            if with_metrics == "full":
                mm = metrics_update(mm, s, cfg.log_cap)
            elif with_metrics == "nohist":
                nodes = s.nodes
                committed = jnp.maximum(mm.committed,
                                        jnp.max(nodes.commit, axis=1))
                mm = mm._replace(committed=committed)
            return (s, mm), None

        (st2, m2), _ = jax.lax.scan(
            body, (st, m), t0 + jnp.arange(CHUNK, dtype=I32))
        return st2, m2

    return go


ORIG = dict(handlers=step_mod._HANDLERS, phase_t=step_mod._phase_t,
            phase_c=step_mod._phase_c, phase_a=step_mod._phase_a)


def apply_variant(name: str) -> str:
    """Patch step internals for the named ablation; returns metrics mode."""
    step_mod._HANDLERS = ORIG["handlers"]
    step_mod._phase_t = ORIG["phase_t"]
    step_mod._phase_c = ORIG["phase_c"]
    step_mod._phase_a = ORIG["phase_a"]
    if name == "full":
        return "full"
    if name == "nometrics":
        return "none"
    if name == "nohist":
        return "nohist"
    if name == "nophaseD":
        step_mod._HANDLERS = ()
        return "full"
    if name.startswith("noh_"):
        # Knock out ONE handler from phase D's chain, attributing its
        # share: noh_ae_req, noh_ae_resp, noh_rv_req, noh_rv_resp,
        # noh_is_req, noh_is_resp.
        target = "_on_" + name[4:]
        keep = tuple(h for h in ORIG["handlers"] if h.__name__ != target)
        assert len(keep) < len(ORIG["handlers"]), name
        step_mod._HANDLERS = keep
        return "full"
    if name == "nodigest":
        # Phase A runs in full but the digest output is frozen, which
        # lets XLA dead-code-eliminate the L-unrolled sequential digest
        # hash chain (and its _payload_at reads). The `applied` counter
        # walk itself still runs — this attributes the DIGEST chain
        # only, not all of the apply loop.
        orig_a = ORIG["phase_a"]

        def thin_apply(cfg, ns, g, i, t):
            ns2 = orig_a(cfg, ns, g, i, t)
            return ns2._replace(digest=ns.digest)
        step_mod._phase_a = thin_apply
        return "full"
    if name == "nophaseT":
        step_mod._phase_t = lambda cfg, ns, out, g, i, t: (ns, out)
        return "full"
    if name == "nophaseC":
        step_mod._phase_c = lambda cfg, ns, g, i, t, csub=None, cpay=None: ns
        return "full"
    if name == "noapply":
        def commit_only(cfg, ns, g, i, t):
            from raft_tpu.core.node import LEADER
            from raft_tpu.ops import quorum
            n = quorum.commit_candidate(ns.match_index, ns.last_index, i,
                                        cfg.k, cfg.majority)
            advance = ((ns.role == LEADER) & (n > ns.commit)
                       & (step_mod._term_at(cfg, ns, n) == ns.term))
            return ns._replace(commit=jnp.where(advance, n, ns.commit))
        step_mod._phase_a = commit_only
        return "full"
    raise ValueError(name)


def run_variant(name: str, n_groups: int, chunks: int = 3):
    mode = apply_variant(name)
    cfg = RaftConfig(seed=42)
    st = sim.init(cfg, n_groups=n_groups)
    m = metrics_init(n_groups)
    go = make_runner(cfg, mode)
    t0 = time.perf_counter()
    st, m = go(st, m, 0)
    jax.block_until_ready(st)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    at = CHUNK
    for _ in range(chunks):
        st, m = go(st, m, at)
        at += CHUNK
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0
    tps = chunks * CHUNK / dt
    log(f"{name:10s} G={n_groups:7d}: {tps:8.1f} ticks/s "
        f"({dt / (chunks * CHUNK) * 1e3:7.2f} ms/tick, compile+warm "
        f"{compile_s:5.1f}s)")
    apply_variant("full")
    return tps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, nargs="+",
                    default=[50_000, 100_000])
    ap.add_argument("--variants", nargs="+",
                    default=["full", "nometrics", "nohist", "nophaseD",
                             "nophaseT", "nophaseC", "noapply"])
    args = ap.parse_args()
    dev = jax.devices()[0]
    log(f"platform: {dev.platform} ({dev.device_kind})")
    results = {}
    for g in args.groups:
        for v in args.variants:
            results[(v, g)] = run_variant(v, g)
    for g in args.groups:
        full = results.get(("full", g))
        if not full:
            continue
        log(f"-- G={g}: attribution vs full ({full:.1f} ticks/s)")
        for v in args.variants:
            if v == "full" or (v, g) not in results:
                continue
            saved = 1e3 / full - 1e3 / results[(v, g)]
            log(f"   {v:10s}: {saved:7.2f} ms/tick attributable")


if __name__ == "__main__":
    main()
