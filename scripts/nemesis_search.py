"""Coverage-guided adversarial nemesis search (DESIGN.md §14).

Mutates gray-failure programs (raft_tpu/nemesis) over a faulted base
universe, scores each candidate run by safety-fold near-misses and
flight-ring health, and keeps a corpus of coverage-novel programs. Any
candidate that actually drops the per-tick safety bit is auto-shrunk
(clause drops + span halvings, `obs.triage`-style violation naming) to
a minimal reproducer and serialized as a self-contained JSON artifact.

The whole hunt is deterministic in --seed: mutation choices are
hash_u32 draws, so a violation found on one box replays everywhere.
Each distinct program is a distinct static config (one XLA compile per
candidate) — size --groups/--ticks like a test, not a bench.

    # hunt (rc 3 + artifact on a violation; rc 0 on a clean budget):
    python scripts/nemesis_search.py --groups 16 --ticks 64 --budget 24
    # replay a checked-in reproducer (rc 1 if it stopped reproducing
    # or names a different tick/leaf):
    python scripts/nemesis_search.py --replay NEMESIS_repro_example.json
    # cross-engine check of the best program found (interpret-mode
    # Pallas vs the XLA scan; any divergence is bisected + shrunk):
    python scripts/nemesis_search.py --budget 12 --check-kernel
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

from raft_tpu.config import RaftConfig
from raft_tpu.nemesis import describe, program_hash
from raft_tpu.nemesis import search as nsearch


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def base_config(seed: int) -> RaftConfig:
    """The search's base universe: light always-on churn (so nemesis
    clauses compose with a live fault background), small ring."""
    return RaftConfig(seed=seed, k=3, log_cap=8, compact_every=4,
                      drop_prob=0.03, crash_prob=0.1, crash_epoch=24)


def _xla_vs_kernel_pair(cfg):
    """Engine pair for the cross-engine differential: the XLA scan vs
    the Pallas kernel in interpret mode (runs on any box)."""
    from raft_tpu.sim import pkernel
    from raft_tpu.sim.run import run

    def xla(s, n, t):
        return run(cfg, s, n, t)[0]

    def kernel(s, n, t):
        return pkernel.prun(cfg, s, n, t, interpret=True)[0]
    return xla, kernel


def replay(path: str, n_groups: int) -> int:
    # Dispatch on artifact kind: model-checker counterexamples
    # (verify/mcheck.py reproducers — an explicit per-tick scheduler
    # trace on the CPU oracle) share the nemesis artifact schema but
    # replay through the checker's own universe, not the XLA engines.
    import json as _json
    with open(path) as fh:
        kind = _json.load(fh).get("kind")
    if kind == "mcheck-reproducer":
        from raft_tpu.verify import mcheck
        art = mcheck.load_reproducer(path)
        log(f"replaying {path}: mcheck scheduler trace, "
            f"{art['n_ticks']} tick(s), mutant "
            f"{art.get('mutant') or '<real oracle>'}, expecting tick "
            f"{art['violation']['tick']} leaf "
            f"{art['violation']['leaf']!r}")
        try:
            rep = mcheck.replay(art)
        except AssertionError as e:
            log(f"REPLAY FAILED: {e}")
            return 1
        log(f"replay ok: tick {rep['tick']} — {rep['predicates']}")
        return 0
    cfg, artifact = nsearch.load_reproducer(path)
    n_ticks = artifact["n_ticks"]
    # The artifact's own run shape wins — the violating group must
    # exist in the replay universe (--groups is only the fallback for
    # pre-n_groups artifacts).
    n_groups = artifact.get("n_groups") or n_groups
    log(f"replaying {path}: {len(cfg.nemesis)} clause(s), "
        f"program {artifact['program_hash']}, engines "
        f"{artifact['engines']!r}, expecting tick "
        f"{artifact['violation']['tick']} leaf "
        f"{artifact['violation']['leaf']!r}")
    inject = artifact.get("inject")
    if inject is not None:
        # A SEEDED violation (--seed-violation / the checked-in
        # example): rebuild the corrupting engine from the recorded
        # parameters and bisect it against the clean scan.
        pair = nsearch.term_corruption_pair(
            inject["tick"], inject["group"], inject["node"],
            inject.get("bump", 4))   # the signature default — a +1
        # fallback could be absorbed by term monotonicity and fail a
        # healthy reproducer
        repro = nsearch.divergence_repro(cfg, pair, n_groups, n_ticks)
    elif artifact["engines"] == "xla-vs-pallas-interpret":
        repro = nsearch.divergence_repro(cfg, _xla_vs_kernel_pair,
                                         n_groups, n_ticks)
    else:
        repro = nsearch.safety_repro(cfg, n_groups, n_ticks)
    try:
        rep = nsearch.verify_reproducer(artifact, repro)
    except AssertionError as e:
        log(f"REPLAY FAILED: {e}")
        return 1
    log(f"replay ok: tick {rep['tick']} — {rep['leaf_report']}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=16)
    ap.add_argument("--ticks", type=int, default=64)
    ap.add_argument("--budget", type=int, default=24,
                    help="mutate-run-score steps (one XLA compile each)")
    ap.add_argument("--seed", type=int, default=0,
                    help="search seed (mutations AND the base universe)")
    ap.add_argument("--out", default="NEMESIS_repro.json",
                    help="where a shrunk violation artifact is written")
    ap.add_argument("--replay", default=None, metavar="ARTIFACT",
                    help="replay a reproducer artifact instead of "
                         "searching (rc 1 on drift); accepts both "
                         "nemesis and verify/mcheck artifacts "
                         "(dispatched on the artifact's `kind`)")
    ap.add_argument("--corpus", default=None, metavar="DIR",
                    help="persist/reload the coverage corpus: seed the "
                         "hunt from every program in DIR, write every "
                         "coverage-novel program back (accumulates "
                         "across runs)")
    ap.add_argument("--check-kernel", action="store_true",
                    help="after the hunt, run the best program through "
                         "the interpret-mode Pallas kernel and bisect "
                         "any divergence from the XLA scan (slow)")
    ap.add_argument("--seed-violation", type=int, default=None,
                    metavar="TICK",
                    help="skip the hunt: inject a known safety "
                         "violation (term flip at TICK, armed only "
                         "while a nemesis clause is active) under the "
                         "canonical gray mix, shrink it, write the "
                         "artifact, and verify it replays — the "
                         "end-to-end self-test of the shrink loop")
    args = ap.parse_args()

    # Pre-flight contract audit (the bench/sweep rule): a hunt over a
    # drifted layout would chase ghosts.
    from raft_tpu import analysis
    analysis.startup_audit(level="static", log=log)

    if args.replay:
        return replay(args.replay, args.groups)

    base = base_config(args.seed)
    if args.seed_violation is not None:
        from raft_tpu.nemesis import gray_mix
        t = args.seed_violation
        prog = gray_mix(args.ticks)
        pair = nsearch.term_corruption_pair(t)
        # chunk=1 keeps the whole shrink on ONE compiled program per
        # candidate config (see term_corruption_pair) — the shrink
        # loop's wall time is XLA compiles, not tick execution.
        repro = nsearch.divergence_repro(base, pair, args.groups,
                                         args.ticks, chunk=1)
        log(f"seeded violation: term flip at tick {t} (armed under the "
            f"program) over {describe(prog)} — shrinking")
        mini, rep = nsearch.shrink(prog, repro, log=log)
        cfg_min = dataclasses.replace(base, nemesis=mini)
        artifact = nsearch.reproducer(
            cfg_min, args.ticks, rep, engines="xla-vs-seeded-corruption",
            inject={"kind": "term_flip", "tick": t, "group": 0,
                    "node": 1, "bump": 4},
            n_groups=args.groups,
            note=f"seeded self-test: nemesis_search --seed-violation {t} "
                 f"--seed {args.seed}")
        nsearch.save_reproducer(args.out, artifact)
        log(f"minimal reproducer ({len(mini)} clause(s), program "
            f"{program_hash(mini)}) -> {args.out}: tick {rep['tick']} "
            f"leaf {rep['leaf']}")
        nsearch.verify_reproducer(artifact, repro)
        log("replay verified: same tick + leaf")
        return 0
    seed_corpus = None
    if args.corpus:
        seed_corpus = nsearch.load_corpus(args.corpus)
        if seed_corpus:
            log(f"corpus: seeded {len(seed_corpus)} program(s) from "
                f"{args.corpus}")
    log(f"hunting: {args.groups} groups x {args.ticks} ticks per "
        f"candidate, budget {args.budget}, seed {args.seed}")
    res = nsearch.search(base, args.groups, args.ticks, args.budget,
                         seed=args.seed, log=log,
                         seed_corpus=seed_corpus)
    log(f"corpus: {len(res['corpus'])} program(s), "
        f"{len(res['coverage'])} coverage signature(s); best score "
        f"{res['best_score']:.1f}: {describe(res['best'])}")
    if args.corpus:
        n = nsearch.save_corpus(args.corpus, res["corpus"])
        log(f"corpus: persisted {n} program(s) -> {args.corpus}")

    rc = 0
    if res["violations"]:
        prog, sig = res["violations"][0]
        log(f"VIOLATION: {sig['unsafe_groups']} unsafe group(s) under "
            f"{describe(prog)} — shrinking")
        repro = nsearch.safety_repro(base, args.groups, args.ticks)
        mini, rep = nsearch.shrink(prog, repro, log=log)
        cfg_min = dataclasses.replace(base, nemesis=mini)
        artifact = nsearch.reproducer(
            cfg_min, args.ticks, rep, engines="xla-safety-fold",
            n_groups=args.groups,
            note=f"found by nemesis_search --seed {args.seed} "
                 f"--budget {args.budget}")
        nsearch.save_reproducer(args.out, artifact)
        log(f"minimal reproducer ({len(mini)} clause(s), program "
            f"{program_hash(mini)}) -> {args.out}: tick {rep['tick']} "
            f"— {rep['leaf_report']}")
        rc = 3

    if args.check_kernel:
        log("cross-engine check: best program through the interpret "
            "kernel vs the XLA scan")
        repro = nsearch.divergence_repro(base, _xla_vs_kernel_pair,
                                         args.groups, args.ticks)
        rep = repro(res["best"])
        if rep is None:
            log("engines bit-identical under the best program")
        else:
            mini, rep = nsearch.shrink(res["best"], repro, log=log)
            cfg_min = dataclasses.replace(base, nemesis=mini)
            artifact = nsearch.reproducer(
                cfg_min, args.ticks, rep,
                engines="xla-vs-pallas-interpret", n_groups=args.groups,
                note="engine divergence found by --check-kernel")
            out = args.out.replace(".json", "_divergence.json")
            nsearch.save_reproducer(out, artifact)
            log(f"ENGINE DIVERGENCE shrunk -> {out}: tick {rep['tick']} "
                f"leaf {rep['leaf']}")
            rc = 3
    return rc


if __name__ == "__main__":
    sys.exit(main())
