"""Budgeted multi-seed nemesis fleet (ISSUE r20, DESIGN.md §19).

One `nemesis_search.py --corpus` hunt explores the mutation space from
ONE seed's deterministic draw sequence; a fleet runs MANY seeds into a
SHARED persisted corpus, so every hunt after the first starts from all
coverage-novel programs the earlier ones found. This driver is the
budgeted loop around that: it spawns one child hunt per seed (each its
own process — one jax runtime per hunt, so a wedged candidate can't
take the fleet down), then triages what the fleet produced:

- violation artifacts are DEDUPED by their (divergent leaf, tick)
  signature — a fleet of N seeds finding the same dropped invariant N
  times is one finding, not N — keeping the reproducer with the
  fewest clauses per signature;
- clean hunts are RANKED by their best near-miss score, so the next
  fleet's attention (more budget, --check-kernel) goes to the seeds
  closest to the edge.

Everything lands in one JSONL fleet report (one record per hunt + a
final summary record), next to the artifacts and the corpus dir:

    python scripts/nemesis_fleet.py --seeds 8 --budget 12 \\
        --groups 16 --ticks 64 --corpus corpus/ --report fleet.jsonl

rc 3 if any hunt found a real violation (the deduped artifacts are the
findings), rc 1 if a child died abnormally, rc 0 on a clean fleet.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

_SEARCH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "nemesis_search.py")

# The child's corpus/score summary line (nemesis_search.py logs it on
# every clean exit); parsed defensively — a None score just ranks last.
_SCORE_RE = re.compile(r"best score (-?[\d.]+):")


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def _corpus_size(dirpath: str) -> int:
    return len(glob.glob(os.path.join(dirpath, "corpus_*.json")))


def run_hunt(seed: int, args) -> dict:
    """One child hunt: its own process, its own artifact path, the
    SHARED corpus dir. Returns the fleet-report record."""
    out = os.path.join(args.out_dir, f"NEMESIS_repro_seed{seed}.json")
    cmd = [sys.executable, _SEARCH, "--seed", str(seed),
           "--budget", str(args.budget), "--groups", str(args.groups),
           "--ticks", str(args.ticks), "--corpus", args.corpus,
           "--out", out]
    if args.check_kernel:
        cmd.append("--check-kernel")
    before = _corpus_size(args.corpus)
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, capture_output=True, text=True)
    wall = time.perf_counter() - t0
    for line in proc.stderr.splitlines():
        log(f"  [seed {seed}] {line}")
    m = _SCORE_RE.search(proc.stderr)
    rec = {"kind": "fleet-hunt", "seed": seed, "rc": proc.returncode,
           "budget": args.budget, "groups": args.groups,
           "ticks": args.ticks, "wall_s": round(wall, 2),
           "best_score": float(m.group(1)) if m else None,
           "corpus_new": _corpus_size(args.corpus) - before,
           "artifact": None, "violation": None}
    if proc.returncode == 3 and os.path.exists(out):
        with open(out) as fh:
            art = json.load(fh)
        rec["artifact"] = out
        rec["violation"] = {"tick": art["violation"]["tick"],
                            "leaf": art["violation"]["leaf"],
                            "program_hash": art["program_hash"],
                            "clauses": len(art["program"])}
    elif proc.returncode not in (0, 3):
        rec["stderr_tail"] = proc.stderr.splitlines()[-5:]
    return rec


def triage(records: list) -> dict:
    """Corpus triage over the fleet's hunt records: dedupe violations
    by (leaf, tick) — keeping the fewest-clause reproducer per
    signature — and rank the clean hunts by best near-miss score."""
    by_sig: dict = {}
    for r in records:
        v = r["violation"]
        if v is None:
            continue
        key = (v["leaf"], v["tick"])
        cur = by_sig.get(key)
        if cur is None or v["clauses"] < cur["violation"]["clauses"]:
            by_sig[key] = r
    ranked = sorted((r for r in records if r["best_score"] is not None),
                    key=lambda r: -r["best_score"])
    return {
        "kind": "fleet-summary",
        "hunts": len(records),
        "violations_total": sum(1 for r in records if r["violation"]),
        "violations_unique": len(by_sig),
        "unique_violations": [
            {"leaf": leaf, "tick": tick,
             "seed": r["seed"], "artifact": r["artifact"],
             "program_hash": r["violation"]["program_hash"],
             "clauses": r["violation"]["clauses"]}
            for (leaf, tick), r in sorted(by_sig.items())],
        "ranked_seeds": [{"seed": r["seed"],
                          "best_score": r["best_score"]}
                         for r in ranked],
        "child_failures": [r["seed"] for r in records
                           if r["rc"] not in (0, 3)],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=4,
                    help="fleet size: hunts run seeds "
                         "[--seed-base, --seed-base + N)")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--budget", type=int, default=12,
                    help="mutate-run-score steps PER HUNT (one XLA "
                         "compile each — the fleet's total compile "
                         "budget is seeds x budget)")
    ap.add_argument("--groups", type=int, default=16)
    ap.add_argument("--ticks", type=int, default=64)
    ap.add_argument("--corpus", default="nemesis_corpus",
                    help="SHARED persisted corpus dir: every hunt "
                         "seeds from it and writes novel programs "
                         "back, so coverage accumulates across the "
                         "fleet (and across fleets)")
    ap.add_argument("--report", default="fleet_report.jsonl",
                    help="JSONL fleet report: one record per hunt + "
                         "a final triaged summary record")
    ap.add_argument("--out-dir", default=".",
                    help="where per-seed violation artifacts land")
    ap.add_argument("--check-kernel", action="store_true",
                    help="pass --check-kernel through to every hunt "
                         "(slow: one interpret-mode kernel run each)")
    args = ap.parse_args()

    os.makedirs(args.corpus, exist_ok=True)
    os.makedirs(args.out_dir, exist_ok=True)
    seeds = range(args.seed_base, args.seed_base + args.seeds)
    log(f"fleet: {args.seeds} hunt(s) x budget {args.budget} "
        f"({args.groups} groups x {args.ticks} ticks per candidate), "
        f"shared corpus {args.corpus!r} "
        f"({_corpus_size(args.corpus)} program(s) seeded)")
    records = []
    with open(args.report, "a") as rep:
        for seed in seeds:
            rec = run_hunt(seed, args)
            records.append(rec)
            rep.write(json.dumps(rec, sort_keys=True) + "\n")
            rep.flush()
            tag = ("VIOLATION" if rec["violation"]
                   else "died" if rec["rc"] not in (0, 3) else "clean")
            log(f"[seed {seed}] {tag} rc={rec['rc']} "
                f"score={rec['best_score']} "
                f"corpus+{rec['corpus_new']} ({rec['wall_s']}s)")
        summary = triage(records)
        summary["corpus_size"] = _corpus_size(args.corpus)
        rep.write(json.dumps(summary, sort_keys=True) + "\n")
    log(f"fleet report -> {args.report}: "
        f"{summary['violations_total']} violation(s), "
        f"{summary['violations_unique']} unique by (leaf, tick); "
        f"corpus {summary['corpus_size']} program(s)")
    for v in summary["unique_violations"]:
        log(f"  finding: leaf={v['leaf']!r} tick={v['tick']} "
            f"program {v['program_hash']} ({v['clauses']} clause(s)) "
            f"-> {v['artifact']}")
    if summary["child_failures"]:
        log(f"  child hunt(s) died abnormally: "
            f"{summary['child_failures']}")
        return 1
    return 3 if summary["violations_unique"] else 0


if __name__ == "__main__":
    sys.exit(main())
