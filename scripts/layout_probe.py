"""Layout experiment: batch-major vs batch-minor for the tick's op mix,
plus the measured bytes/group report behind the G-ceiling math.

The batched state is `[G, K, L]` (G ~ 1e5 groups, K = 5 replicas,
L = 32 ring slots). XLA tiles the two MINOR dims onto the TPU's
(8 sublane, 128 lane) registers: with K/L minor, a [G, K] array pads
5 -> 128 lanes (25x waste) and [G, K, L] pads (5, 32) -> (8, 128)
(6.4x). Putting G minor instead makes every vector op lane-dense.

This probe times the same per-node one-hot select/reduce chain (the
phase-D workhorse pattern) under both layouts via vmap in_axes alone —
identical trace, different physical layout — to decide whether flipping
the state layout is worth the refactor. Results recorded in DESIGN.md §7.

`--bytes-only` (or just reading the report the default run prints
first) gives the per-leaf bytes/group of the State pytree AND of the
kernel wire form, with the single-chip G ceiling each implies per
16 GiB HBM — the measured starting point for the packed-state-layout
work (ROADMAP item on cutting bytes/group) and the multichip sweep's
`predicted` block (DESIGN.md §9).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # runnable as `python scripts/...`

import jax
import jax.numpy as jnp
import numpy as np

G, K, L, STEPS, REPS = 100_000, 5, 32, 30, 3


def one(lt, idx):
    """Per-node toy kernel: 8 chained one-hot reads + masked writes over
    an [L] ring — the shape of _lget/_lset chains in sim/step.py."""
    lanes = jnp.arange(L, dtype=jnp.int32)
    for _ in range(8):
        v = jnp.sum(jnp.where(lanes == idx, lt, 0), -1)
        lt = jnp.where((lanes == idx) & (v > 0), lt + 1, lt)
        idx = (idx + v + 1) % L
    return lt, idx


def scanner(f):
    @jax.jit
    def go(lt, idx):
        def body(c, _):
            return f(*c), None
        (lt2, idx2), _ = jax.lax.scan(body, (lt, idx), None, length=STEPS)
        return lt2, idx2
    return go


def bench(name, f, lt, idx):
    go = scanner(f)
    out = go(lt, idx)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = go(lt, idx)
        s = float(jnp.sum(out[0]))   # forces the full result
        best = min(best, time.perf_counter() - t0)
    per_step_ms = best / STEPS * 1e3
    print(f"{name}: {per_step_ms:7.4f} ms/step ({best * 1e3:.3f} ms best of "
          f"{REPS}, checksum {s:.0f})")
    return per_step_ms


def bytes_per_group_report(cfg=None):
    """Print per-leaf bytes/group for (a) the State pytree the XLA path
    scans and (b) the kernel wire form (sim/pkernel.py), and the
    single-chip G ceiling each implies for a 16 GiB HBM. All numbers
    are derived from the real dtypes/shapes (a 1-group state is
    materialized and walked), not estimated."""
    from raft_tpu import sim
    from raft_tpu.config import RaftConfig
    from raft_tpu.obs.recorder import RING
    from raft_tpu.sim import pkernel

    cfg = cfg or RaftConfig(seed=42)
    st = sim.init(cfg, n_groups=1)
    print(f"bytes/group, headline config (k={cfg.k}, L={cfg.log_cap}, "
          f"E={cfg.max_entries_per_msg}):")
    total = 0
    rows = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(st)[0]:
        name = ".".join(getattr(p, "name", str(getattr(p, "idx", "?")))
                        for p in path)
        b = np.dtype(leaf.dtype).itemsize * int(np.prod(leaf.shape[1:],
                                                        dtype=np.int64))
        rows.append((b, name, str(leaf.dtype), leaf.shape[1:]))
        total += b
    rows.sort(reverse=True)
    for b, name, dt, shp in rows:
        print(f"  {b:6d} B  {name:28s} {dt}{list(shp)}")
    print(f"  state total: {total} B/group "
          f"(+ flight recorder {6 * RING * 4} B/group when recording)")

    wire_nf = 4 * pkernel.wire_words_per_group(cfg, with_flight=False)
    wire = 4 * pkernel.wire_words_per_group(cfg, with_flight=True)
    hist_b = 4 * pkernel.HIST_SIZE
    print(f"kernel wire form: {wire} B/group with the flight ring "
          f"({wire_nf} B without), of which in-kernel histogram "
          f"{hist_b} B + flight {wire - wire_nf} B — per-GROUP on the "
          f"wire, unlike the XLA path's global [H] histogram")
    hbm = pkernel.HBM_LIMIT_BYTES
    print(f"implied single-chip G ceiling per {hbm >> 30} GiB HBM "
          f"(2x in+out buffers, no donation, whole 1024-group blocks "
          f"— the exact supported() boundary):")
    print(f"  kernel wire (flight on):  "
          f"{pkernel.hbm_ceiling_groups(cfg):>9,d} groups")
    print(f"  kernel wire (flight off): "
          f"{pkernel.hbm_ceiling_groups(cfg, with_flight=False):>9,d} "
          f"groups")
    print(f"  state only (XLA resident set, excl. scan intermediates): "
          f"{hbm // total:>9,d} groups")
    for d in (4, 8):
        print(f"  x{d} devices (kernel, flight on): "
              f"{pkernel.hbm_ceiling_groups(cfg, n_devices=d):>9,d} groups")

    # Streamed (cohort-paged) ceiling (DESIGN.md §15): with
    # stream_groups on, HBM holds only the resident cohort window and
    # host RAM becomes the binding resource — the ceiling is
    # host_limit // wire-bytes-per-block, whole blocks, and the model
    # is pinned to the exact supported() boundary just like the static
    # one above.
    import dataclasses as _sdc
    scfg = _sdc.replace(cfg, stream_groups=True)
    host = pkernel.HOST_RAM_LIMIT_BYTES
    print(f"streamed (cohort-paged) G ceiling per {host >> 30} GiB host "
          f"RAM (cohort_blocks={scfg.cohort_blocks}, "
          f"{pkernel._stream_windows(scfg)} HBM windows of "
          f"{pkernel.cohort_hbm_bytes(scfg) >> 20} MiB — DESIGN.md §15):")
    for fl_label, fl in (("flight on", True), ("flight off", False)):
        ceil = pkernel.streamed_ceiling_groups(scfg, with_flight=fl)
        boundary = (pkernel.supported(scfg, n_groups=ceil, with_flight=fl)
                    and not pkernel.supported(scfg, n_groups=ceil
                                              + pkernel.GB, with_flight=fl))
        print(f"  kernel wire ({fl_label}): {ceil:>12,d} groups "
              f"({'exact supported() boundary' if boundary else 'BOUNDARY DRIFT'})")
    adcfg = _sdc.replace(scfg, pack_bools=True, pack_ring=True,
                         alias_wire=True, wire_hist=False)
    ad = pkernel.streamed_ceiling_groups(adcfg, with_flight=False)
    st = pkernel.hbm_ceiling_groups(adcfg, with_flight=False)
    print(f"  all dials, flight off:   {ad:>12,d} groups "
          f"(vs {st:,d} static resident = {ad / st:.2f}x)")
    # r17 sharded paging (DESIGN.md §16): every chip pages its own
    # whole-block window slice, and host RAM is a PER-DEVICE allocation
    # (one host per chip group on a pod) — so the streamed ceiling
    # scales with the device axis, boundary-exact at every N.
    one = pkernel.streamed_ceiling_groups(scfg)
    for d in (4, 8):
        ceil_d = pkernel.streamed_ceiling_groups(scfg, n_devices=d)
        boundary = (pkernel.supported(scfg, n_groups=ceil_d, n_devices=d)
                    and not pkernel.supported(scfg,
                                              n_groups=ceil_d + pkernel.GB,
                                              n_devices=d))
        print(f"  x{d} devices (sharded paging, flight on): "
              f"{ceil_d:>12,d} groups ({ceil_d / one:.2f}x 1-dev, "
              f"{pkernel.stream_blocks_per_device(scfg, d)} blocks/device"
              f"/window, "
              f"{'exact supported() boundary' if boundary else 'BOUNDARY DRIFT'})")

    # Client-traffic delta (DESIGN.md §10): the headline config with
    # the bench client-SLO segment's workload knobs on.
    import dataclasses
    ccfg = dataclasses.replace(cfg, sessions=True, cmds_per_tick=0,
                               client_rate=0.2, client_slots=4,
                               client_retry_backoff=8)
    from raft_tpu.clients.state import CLIENT_LEAVES
    cwire = 4 * pkernel.wire_words_per_group(ccfg, with_flight=True)
    s = ccfg.client_slots
    n_cl = len(CLIENT_LEAVES)
    parts = {
        "session tables (2 x [K, S] i32)": 2 * cfg.k * s * 4,
        "IS mailbox session payload ([K, K, S])": cfg.k * cfg.k * s * 4,
        f"client state ({s} slots x {n_cl} leaves)": n_cl * s * 4,
        "client SLO lanes (acked/retries/max_lat)": 3 * 4,
        "client ack-latency histogram rows": 4 * pkernel.HIST_SIZE,
    }
    print(f"client traffic delta (slots={s}, DESIGN.md §10): "
          f"wire {cwire} B/group (+{cwire - wire} B):")
    for name, b in parts.items():
        print(f"  {b:6d} B  {name}")
    print(f"  client-universe single-chip G ceiling (flight on): "
          f"{pkernel.hbm_ceiling_groups(ccfg):>9,d} groups "
          f"(vs {pkernel.hbm_ceiling_groups(cfg):,d} without clients)")

    # Derived-model reconciliation + widening-waste block (DESIGN.md
    # §11): the engine-contract auditor recomputes every number above
    # from dtype x shape and names the i32-widened bool leaves — the
    # measured starting point for the packed-layout work (ROADMAP
    # item 2). Any derived-vs-pinned disagreement prints here AND
    # fails `scripts/static_audit.py`.
    from raft_tpu.analysis import bytemodel
    for label, c in (("clients-off", cfg), ("clients-on", ccfg)):
        model = bytemodel.derived_wire_model(c)
        verdict = "derived == pinned" if not model["problems"] else \
            "; ".join(model["problems"])
        print(f"derived wire model [{label}]: "
              f"{model['wire_bytes_derived']} B/group ({verdict})")
    w = bytemodel.derived_wire_model(cfg)["widening"]
    print(f"i32-widened bool leaves ({len(w['leaves'])} — Mosaic "
          f"transports no i1 vectors, so each bool word burns 3 wire "
          f"bytes UNLESS the pack_bools dial bit-packs it, DESIGN.md "
          f"§13): {w['waste_bytes_per_group']} B/group of widening "
          f"waste (wire {w['wire_bytes']} B vs {w['native_bytes']} B "
          f"if i8):")
    for name in w["leaves"]:
        print(f"    {name}")
    import dataclasses as _dc
    pcfg = _dc.replace(cfg, pack_bools=True, pack_ring=True)
    pm = bytemodel.derived_wire_model(pcfg)
    pverdict = "derived == pinned" if not pm["problems"] else \
        "; ".join(pm["problems"])
    print(f"derived wire model [packed, bools+ring]: "
          f"{pm['wire_bytes_derived']} B/group ({pverdict}); run "
          f"--ablate for the full per-encoding table + ceilings")


# The r13 encoding ablation (DESIGN.md §13): one row per layout-dial
# combination, additive order — each row's delta against the previous
# is that encoding's price. (label, knob dict, with_flight).
ABLATION_ROWS = (
    ("baseline (r12 wire)", {}, True),
    ("+pack_bools", dict(pack_bools=True), True),
    ("+pack_ring", dict(pack_bools=True, pack_ring=True), True),
    ("+alias_wire", dict(pack_bools=True, pack_ring=True,
                         alias_wire=True), True),
    ("+wire_hist off", dict(pack_bools=True, pack_ring=True,
                            alias_wire=True, wire_hist=False), True),
    ("+flight off (all dials)", dict(pack_bools=True, pack_ring=True,
                                     alias_wire=True, wire_hist=False),
     False),
)


def _measure_ticks_per_sec(cfg, n_groups: int, ticks: int,
                           with_flight: bool):
    """Steady-state kernel ticks/s at one layout (bench warmup
    protocol: 2 compile-absorbing chunks, timed chunks closed by the
    counter fetch). TPU only — the caller gates."""
    from raft_tpu import sim
    from raft_tpu.obs import flight_init
    from raft_tpu.sim import pkernel

    chunk = 200
    fl = flight_init(n_groups) if with_flight else None
    leaves, g = pkernel.kinit(cfg, sim.init(cfg, n_groups=n_groups),
                              flight=fl)
    leaves = pkernel.kstep(cfg, leaves, 0, chunk)
    pkernel.kcommitted(cfg, leaves, g)
    leaves = pkernel.kstep(cfg, leaves, chunk, chunk)
    pkernel.kcommitted(cfg, leaves, g)
    n_chunks = max(1, ticks // chunk)
    t0 = time.perf_counter()
    for c in range(n_chunks):
        leaves = pkernel.kstep(cfg, leaves, (c + 2) * chunk, chunk)
    pkernel.kcommitted(cfg, leaves, g)   # fetch closes the timer
    return n_chunks * chunk / (time.perf_counter() - t0)


def ablation_table(measure: bool, groups: int, ticks: int):
    """The per-encoding toggle table (ISSUE r13 satellite, recorded in
    DESIGN.md §13): wire B/group, modeled single-chip ceiling (the
    exact supported() boundary, residency multiplier included), and —
    where a TPU is attached — measured steady-state ticks/s per row so
    any encoding that does not pay is caught here and reverted to
    default-off."""
    import dataclasses

    import jax

    from raft_tpu.config import RaftConfig
    from raft_tpu.sim import pkernel

    base = RaftConfig(seed=42)
    on_tpu = jax.devices()[0].platform == "tpu"
    if measure and not on_tpu:
        print("(no TPU attached: measured column is modeled-only — "
              "the driver's --ablate run fills it)")
    print(f"encoding ablation, headline config (k={base.k}, "
          f"L={base.log_cap}; HBM {pkernel.HBM_LIMIT_BYTES >> 30} GiB):")
    print(f"  {'encoding':28s} {'B/group':>8s} {'x res':>5s} "
          f"{'ceiling groups':>14s} {'measured ticks/s':>16s}")
    prev_ceiling = None
    for label, knobs, with_flight in ABLATION_ROWS:
        cfg = dataclasses.replace(base, **knobs)
        wire = 4 * pkernel.wire_words_per_group(cfg,
                                                with_flight=with_flight)
        ceiling = pkernel.hbm_ceiling_groups(cfg, with_flight=with_flight)
        measured = "-"
        if measure and on_tpu:
            try:
                tps = _measure_ticks_per_sec(cfg, groups, ticks,
                                             with_flight)
                measured = f"{tps:,.1f}"
            except Exception as e:   # a row must never kill the table
                measured = f"error: {type(e).__name__}"
        gain = ""
        if prev_ceiling:
            gain = f"  ({ceiling / prev_ceiling:.2f}x)"
        print(f"  {label:28s} {wire:8,d} "
              f"{pkernel._residency_buffers(cfg):>5d} "
              f"{ceiling:>14,d} {measured:>16s}{gain}")
        prev_ceiling = ceiling
    all_cfg = dataclasses.replace(base, **ABLATION_ROWS[-1][1])
    r12 = pkernel.hbm_ceiling_groups(base)
    full = pkernel.hbm_ceiling_groups(all_cfg, with_flight=False)
    print(f"  all dials vs r12 baseline: {full:,d} / {r12:,d} groups = "
          f"{full / r12:.2f}x the modeled single-chip ceiling")


# The r19 narrow-native ablation (DESIGN.md §18): cumulative dial
# rows — each row's resident delta against the previous is that dial's
# price. donate_scan rides last: it halves scan RESIDENCY buffers, not
# bytes/group, so its row moves the "x res" column only.
NARROW_ABLATION_ROWS = (
    ("wide (r18 resident)", {}),
    ("+narrow_scalars", dict(narrow_scalars=True)),
    ("+narrow_ring", dict(narrow_scalars=True, narrow_ring=True)),
    ("+narrow_mailbox", dict(narrow_scalars=True, narrow_ring=True,
                             narrow_mailbox=True)),
    ("+narrow_clients", dict(narrow_scalars=True, narrow_ring=True,
                             narrow_mailbox=True, narrow_clients=True)),
    ("+donate_scan (all dials)", dict(narrow_scalars=True,
                                      narrow_ring=True,
                                      narrow_mailbox=True,
                                      narrow_clients=True,
                                      donate_scan=True)),
)


def _measure_xla_ticks_per_sec(cfg, n_groups: int, ticks: int) -> float:
    """Steady-state XLA-scan ticks/s under `cfg`'s narrow dials —
    CPU-honest: runs on whatever backend is attached and the table
    labels the platform, because the narrow claim here is "no tick-rate
    cliff from the boundary casts", which a CPU box can falsify."""
    from raft_tpu import sim
    from raft_tpu.sim.run import metrics_init, run

    cl = bool(cfg.clients_u32)
    st = sim.init(cfg, n_groups=n_groups)
    m = metrics_init(n_groups, clients=cl)
    st, m = run(cfg, st, ticks, metrics=m)          # compile + warm
    jax.block_until_ready(st)
    best = float("inf")
    for _ in range(3):
        st2 = sim.init(cfg, n_groups=n_groups)
        m2 = metrics_init(n_groups, clients=cl)
        t0 = time.perf_counter()
        st2, m2 = run(cfg, st2, ticks, metrics=m2)
        jax.block_until_ready(st2)
        best = min(best, time.perf_counter() - t0)
    return ticks / best


def narrow_ablation_table(measure: bool, groups: int, ticks: int):
    """The r19 native-dtype column of --ablate (DESIGN.md §18):
    per-dial RESIDENT bytes/group (the XLA scan carry — the kernel
    wire is dial-invariant and stays in the r13 table above), the
    derived-vs-pinned verdict from the four-way reconciled byte model,
    the per-leaf wide-vs-narrow table, and a measured XLA ticks/s
    column (CPU-honest: labeled with the attached platform)."""
    import dataclasses

    from raft_tpu.analysis import bytemodel
    from raft_tpu.config import RaftConfig
    from raft_tpu.obs.roofline import tick_byte_model

    cbase = dataclasses.replace(RaftConfig(seed=42), sessions=True,
                                cmds_per_tick=0, client_rate=0.2,
                                client_slots=4, client_retry_backoff=8)
    platform = jax.devices()[0].platform
    print(f"narrow-native resident ablation (DESIGN.md §18; XLA scan "
          f"carry, flight off; measured on {platform}, "
          f"G={groups:,}, {ticks} ticks):")
    print(f"  {'dials':28s} {'resident B/g':>12s} {'cut':>7s} "
          f"{'x res':>5s} {'measured ticks/s':>16s}")
    prev = None
    for label, knobs in NARROW_ABLATION_ROWS:
        # All rows ride the clients universe so the cumulative deltas
        # stay additive through the +narrow_clients row; the headline
        # (clients-off) pair prints in the verdict line below.
        cfg = dataclasses.replace(cbase, **knobs)
        model = bytemodel.resident_bytes_model(cfg)
        resident = model["resident_bytes_narrow"]
        cut = f"-{model['reduction_pct']:.1f}%"
        measured = "-"
        if measure:
            try:
                tps = _measure_xla_ticks_per_sec(cfg, groups, ticks)
                measured = f"{tps:,.1f}"
            except Exception as e:   # a row must never kill the table
                measured = f"error: {type(e).__name__}"
        note = ""
        if prev is not None and resident != prev:
            note = f"  (-{prev - resident} B)"
        xres = tick_byte_model(cfg, groups, "xla",
                               with_flight=False)["scan_residency_buffers"]
        print(f"  {label:28s} {resident:12,d} {cut:>7s} "
              f"{xres:>5d} {measured:>16s}{note}")
        prev = resident
    probs = bytemodel.narrow_model_problems()
    verdict = ("derived == pinned (4034 -> 2494 headline, 4734 -> 2842 "
               "clients; all four accountings agree)" if not probs
               else "; ".join(probs))
    print(f"  narrow byte model verdict: {verdict}")
    ncfg = bytemodel.all_dials_cfg(cbase)
    model = bytemodel.resident_bytes_model(ncfg)
    narrowed = [r for r in model["leaves"] if r["narrowed"]]
    print(f"  per-leaf wide -> narrow (clients universe, "
          f"{len(narrowed)} leaves narrowed):")
    for r in sorted(narrowed, key=lambda r: r["bytes_wide"]
                    - r["bytes_narrow"], reverse=True):
        print(f"    {r['bytes_wide']:5d} -> {r['bytes_narrow']:4d} B  "
              f"{r['name']:32s} {r['dtype_wide']} -> {r['dtype_narrow']}"
              f"{r['shape_per_group']}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bytes-only", action="store_true",
                    help="print the bytes/group + G-ceiling report and "
                    "exit (no timing probe)")
    ap.add_argument("--ablate", action="store_true",
                    help="print the r13 encoding-ablation table "
                    "(DESIGN.md §13): per-dial wire bytes + modeled "
                    "ceiling + measured ticks/s on a TPU; exit")
    ap.add_argument("--ablate-groups", type=int, default=100_000,
                    help="group count for the measured ablation column")
    ap.add_argument("--ablate-ticks", type=int, default=600,
                    help="timed ticks for the measured ablation column")
    ap.add_argument("--staging-ablation", action="store_true",
                    help="measure the r17 copy path (DESIGN.md §16): "
                    "staged per-device window commits (preallocated "
                    "host staging + N concurrent device_put streams) "
                    "vs the naive device_put loop, on every visible "
                    "device; exit")
    args = ap.parse_args()
    if args.staging_ablation:
        import dataclasses as _dc

        from raft_tpu import parallel
        from raft_tpu.config import RaftConfig
        from raft_tpu.parallel import stream_sched
        nd = len(jax.devices())
        mesh = parallel.make_mesh(nd)
        cfg = _dc.replace(RaftConfig(seed=42),
                          stream_groups=True, cohort_blocks=1)
        rep = stream_sched.staging_ablation(cfg, mesh)
        print(f"staging ablation ({rep['n_devices']} device(s), "
              f"{rep['window_bytes'] / 2**20:.1f} MiB/window x "
              f"{rep['windows']} windows, best of 3):")
        print(f"  staged: {rep['staged_wall_s'] * 1e3:8.1f} ms  "
              f"({rep['staged_mib_s']:,.0f} MiB/s)")
        print(f"  naive:  {rep['naive_wall_s'] * 1e3:8.1f} ms  "
              f"({rep['naive_mib_s']:,.0f} MiB/s)")
        print(f"  staged/naive speedup: {rep['staged_over_naive']:.3f}x "
              f"(>1 = staged wins; the TPU column is the bandwidth "
              f"claim, a CPU box only proves the protocol)")
        return
    if args.ablate:
        ablation_table(True, args.ablate_groups, args.ablate_ticks)
        print()
        narrow_ablation_table(True, min(args.ablate_groups, 4096),
                              min(args.ablate_ticks, 64))
        return
    bytes_per_group_report()
    if args.bytes_only:
        return

    print(f"platform: {jax.devices()[0].device_kind}, G={G} K={K} L={L}")
    key = jax.random.PRNGKey(0)
    lt_gkl = jax.random.randint(key, (G, K, L), 0, 5, jnp.int32)
    idx_gkl = jax.random.randint(key, (G, K), 0, L, jnp.int32)
    lt_klg = jnp.transpose(lt_gkl, (1, 2, 0))
    idx_klg = jnp.transpose(idx_gkl, (1, 0))

    f_gkl = jax.vmap(jax.vmap(one))                     # [G, K, L]: G major
    f_klg = jax.vmap(jax.vmap(one, 0, 0), -1, -1)       # [K, L, G]: G minor

    a = bench("G-major [G,K,L]", f_gkl, lt_gkl, idx_gkl)
    b = bench("G-minor [K,L,G]", f_klg, lt_klg, idx_klg)
    print(f"speedup G-minor: {a / b:.2f}x")


if __name__ == "__main__":
    main()
