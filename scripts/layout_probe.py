"""Layout experiment: batch-major vs batch-minor for the tick's op mix.

The batched state is `[G, K, L]` (G ~ 1e5 groups, K = 5 replicas,
L = 32 ring slots). XLA tiles the two MINOR dims onto the TPU's
(8 sublane, 128 lane) registers: with K/L minor, a [G, K] array pads
5 -> 128 lanes (25x waste) and [G, K, L] pads (5, 32) -> (8, 128)
(6.4x). Putting G minor instead makes every vector op lane-dense.

This probe times the same per-node one-hot select/reduce chain (the
phase-D workhorse pattern) under both layouts via vmap in_axes alone —
identical trace, different physical layout — to decide whether flipping
the state layout is worth the refactor. Results recorded in DESIGN.md §7.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

G, K, L, STEPS, REPS = 100_000, 5, 32, 30, 3


def one(lt, idx):
    """Per-node toy kernel: 8 chained one-hot reads + masked writes over
    an [L] ring — the shape of _lget/_lset chains in sim/step.py."""
    lanes = jnp.arange(L, dtype=jnp.int32)
    for _ in range(8):
        v = jnp.sum(jnp.where(lanes == idx, lt, 0), -1)
        lt = jnp.where((lanes == idx) & (v > 0), lt + 1, lt)
        idx = (idx + v + 1) % L
    return lt, idx


def scanner(f):
    @jax.jit
    def go(lt, idx):
        def body(c, _):
            return f(*c), None
        (lt2, idx2), _ = jax.lax.scan(body, (lt, idx), None, length=STEPS)
        return lt2, idx2
    return go


def bench(name, f, lt, idx):
    go = scanner(f)
    out = go(lt, idx)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = go(lt, idx)
        s = float(jnp.sum(out[0]))   # forces the full result
        best = min(best, time.perf_counter() - t0)
    per_step_ms = best / STEPS * 1e3
    print(f"{name}: {per_step_ms:7.4f} ms/step ({best * 1e3:.3f} ms best of "
          f"{REPS}, checksum {s:.0f})")
    return per_step_ms


def main():
    print(f"platform: {jax.devices()[0].device_kind}, G={G} K={K} L={L}")
    key = jax.random.PRNGKey(0)
    lt_gkl = jax.random.randint(key, (G, K, L), 0, 5, jnp.int32)
    idx_gkl = jax.random.randint(key, (G, K), 0, L, jnp.int32)
    lt_klg = jnp.transpose(lt_gkl, (1, 2, 0))
    idx_klg = jnp.transpose(idx_gkl, (1, 0))

    f_gkl = jax.vmap(jax.vmap(one))                     # [G, K, L]: G major
    f_klg = jax.vmap(jax.vmap(one, 0, 0), -1, -1)       # [K, L, G]: G minor

    a = bench("G-major [G,K,L]", f_gkl, lt_gkl, idx_gkl)
    b = bench("G-minor [K,L,G]", f_klg, lt_klg, idx_klg)
    print(f"speedup G-minor: {a / b:.2f}x")


if __name__ == "__main__":
    main()
